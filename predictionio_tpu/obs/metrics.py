"""Lock-cheap metrics registry: counters, gauges, fixed-bucket histograms.

The reference shipped one coarse per-hour ``Stats`` map
(``data/.../api/Stats.scala``); a TPU serving fleet needs per-request
latency distributions, queue depths, breaker states and XLA recompile
counts, scrapeable by Prometheus. Design constraints:

- **Hot-path cheap.** ``inc``/``observe`` touch one small per-metric lock
  around a couple of float ops — no global registry lock, no allocation
  after the first observation of a label set. The registry lock is taken
  only at instrument creation and scrape time.
- **Fixed buckets.** Histograms use a declared bucket ladder (default
  tuned for serving latency: 100us..10s) so concurrent writers only ever
  increment integers; p50/p95/p99 are extracted at read time by walking
  the cumulative counts (log-linear interpolation inside the bucket).
- **Prometheus text format.** ``render_prometheus()`` emits the v0.0.4
  exposition format (``# HELP``/``# TYPE``, ``_bucket{le=...}``/``_sum``/
  ``_count`` for histograms) so a stock Prometheus scrape of ``/metrics``
  works with zero adapters. ``snapshot()`` is the JSON twin for bench
  output and dashboards.

This module must stay importable without jax/numpy: the event server and
``pio top`` use it and neither should drag in an accelerator runtime.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Any, Callable, Iterable

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# serving-latency ladder in seconds: 100us up to 10s, roughly 2-2.5x steps.
# Fixed (not exponential-growing) so every writer only increments ints.
DEFAULT_BUCKETS = (
    0.0001,
    0.00025,
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


def format_value(v: float) -> str:
    """Prometheus sample value: integers render without a trailing .0."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared label plumbing. Subclasses hold per-labelset state in
    ``_series`` keyed by the tuple of label values (in ``labelnames``
    order) and guard it with one small lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: tuple[str, ...] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: dict[tuple[str, ...], Any] = {}

    def _key(self, labels: dict[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def collect(self) -> list[tuple[tuple[str, ...], Any]]:
        """Snapshot of (label_values, state) pairs, stable order."""
        with self._lock:
            return sorted(self._series.items())

    def remove(self, **labels: str) -> bool:
        """Drop one labeled series from the exposition (no-op when it was
        never observed). The fleet uses this when a replica is retired:
        a gauge for a worker that no longer exists is not 'zero', it is
        *gone* — rendering it forever reads as a live-but-down replica."""
        key = self._key(labels)
        with self._lock:
            return self._series.pop(key, None) is not None

    def prune(self, label_name: str, keep: Iterable[str]) -> int:
        """Reconcile-against-live-set: drop every series whose value for
        ``label_name`` is not in ``keep`` (the same discipline PR 10
        applied to ``pio_ann_index_*``). Returns how many series were
        dropped. Unlabeled metrics and metrics without ``label_name``
        are left untouched."""
        if label_name not in self.labelnames:
            return 0
        idx = self.labelnames.index(label_name)
        keep_set = {str(v) for v in keep}
        with self._lock:
            dead = [k for k in self._series if k[idx] not in keep_set]
            for k in dead:
                del self._series[k]
            return len(dead)

    def render(self, exemplars: bool = False) -> list[str]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing float. ``set_total`` exists to mirror a
    counter maintained elsewhere (e.g. the micro-batcher's plain-int
    trip counts) without double bookkeeping — it clamps to monotonic."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labelnames: tuple[str, ...] = ()):
        super().__init__(name, help, labelnames)
        if not labelnames:
            # an unlabeled counter scrapes as an explicit 0 before its
            # first increment — "shed happened zero times" is a signal,
            # a missing series is a dashboard hole
            self._series[()] = 0.0

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def set_total(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = max(self._series.get(key, 0.0), float(value))

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())

    def render(self, exemplars: bool = False) -> list[str]:
        return [
            f"{self.name}{_format_labels(self.labelnames, k)} {format_value(v)}"
            for k, v in self.collect()
        ]


class Gauge(_Metric):
    """Point-in-time value. ``set_function`` registers a callback read at
    collect time (queue depth, breaker state) so the hot path pays
    nothing for gauges that merely mirror existing state."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labelnames: tuple[str, ...] = ()):
        super().__init__(name, help, labelnames)
        if not labelnames:
            self._series[()] = 0.0  # same explicit-zero contract as Counter

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn: Callable[[], float], **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = fn

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            v = self._series.get(key, 0.0)
        return float(v() if callable(v) else v)

    def render(self, exemplars: bool = False) -> list[str]:
        out = []
        for k, v in self.collect():
            if callable(v):
                try:
                    v = float(v())
                except Exception:
                    continue  # a failing callback must not break the scrape
            out.append(
                f"{self.name}{_format_labels(self.labelnames, k)} {format_value(v)}"
            )
        return out


class _HistogramSeries:
    __slots__ = ("counts", "count", "sum", "max", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 for the +Inf bucket
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        # bucket index -> (exemplar_id, observed_value): the most recent
        # trace id observed into each bucket, so a tail bucket links to a
        # concrete trace in /traces/recent (OpenMetrics exemplars)
        self.exemplars: dict[int, tuple[str, float]] = {}


class Histogram(_Metric):
    """Fixed-bucket histogram with percentile extraction.

    ``observe`` is O(log B) (bisect into the bucket ladder) under the
    metric lock; percentiles walk cumulative counts at read time and
    interpolate inside the winning bucket, which is exact enough for
    p50/p95/p99 dashboards (error bounded by bucket width).
    """

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labelnames: tuple[str, ...] = (),
        buckets: Iterable[float] | None = None,
    ):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs

    def observe(
        self, value: float, exemplar: str | None = None, **labels: str
    ) -> None:
        """Record one observation. ``exemplar`` (typically the request's
        trace id) is kept per bucket — last writer wins — and rendered in
        the OpenMetrics exposition so a p99 outlier links to a concrete
        trace instead of being an anonymous count."""
        import bisect

        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistogramSeries(len(self.buckets))
            series.counts[i] += 1
            series.count += 1
            series.sum += value
            if value > series.max:
                series.max = value
            if exemplar:
                series.exemplars[i] = (str(exemplar), value)

    def _snapshot_series(self, key: tuple[str, ...]) -> _HistogramSeries | None:
        with self._lock:
            series = self._series.get(key)
            if series is None:
                return None
            copy = _HistogramSeries(len(self.buckets))
            copy.counts = list(series.counts)
            copy.count = series.count
            copy.sum = series.sum
            copy.max = series.max
            copy.exemplars = dict(series.exemplars)
            return copy

    def _percentile_of(self, series: _HistogramSeries, q: float) -> float:
        if series.count == 0:
            return 0.0
        target = q * series.count
        acc = 0
        for i, c in enumerate(series.counts):
            prev_acc = acc
            acc += c
            if acc >= target:
                if i >= len(self.buckets):  # +Inf bucket: no upper bound
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (target - prev_acc) / c if c else 1.0
                return lo + (hi - lo) * frac
        return self.buckets[-1]

    def percentile(self, q: float, **labels: str) -> float:
        series = self._snapshot_series(self._key(labels))
        if series is None:
            return 0.0
        return self._percentile_of(series, q)

    def bucket_counts(self, **labels: str) -> list[int]:
        """Per-bucket observation counts snapshot (last slot = +Inf); a
        zero vector when the series doesn't exist yet. Callers keep this
        as a baseline and hand the elementwise delta of two snapshots to
        :meth:`percentile_from_counts` — percentiles over a *window*,
        which a lifetime histogram cannot answer directly."""
        series = self._snapshot_series(self._key(labels))
        if series is None:
            return [0] * (len(self.buckets) + 1)
        return list(series.counts)

    def percentile_from_counts(self, counts: list[int], q: float) -> float:
        """Percentile over an externally supplied bucket-count vector
        (e.g. a delta of two :meth:`bucket_counts` snapshots)."""
        series = _HistogramSeries(len(self.buckets))
        series.counts = list(counts)
        series.count = sum(counts)
        return self._percentile_of(series, q)

    def exemplars(self, **labels: str) -> dict[str, dict[str, Any]]:
        """``{bucket_le: {"exemplar": id, "value": seconds}}`` snapshot of
        the per-bucket exemplars (``le`` formatted like the exposition,
        ``+Inf`` for the overflow bucket); empty when none captured."""
        series = self._snapshot_series(self._key(labels))
        if series is None:
            return {}
        out: dict[str, dict[str, Any]] = {}
        for i, (ex, v) in sorted(series.exemplars.items()):
            le = (
                format_value(self.buckets[i])
                if i < len(self.buckets)
                else "+Inf"
            )
            out[le] = {"exemplar": ex, "value": v}
        return out

    def summary(self, **labels: str) -> dict[str, float]:
        """One consistent snapshot -> count/mean/p50/p95/p99/sum (seconds)."""
        series = self._snapshot_series(self._key(labels))
        if series is None or series.count == 0:
            return {"count": 0}
        return {
            "count": series.count,
            "sum": series.sum,
            "mean": series.sum / series.count,
            "p50": self._percentile_of(series, 0.50),
            "p95": self._percentile_of(series, 0.95),
            "p99": self._percentile_of(series, 0.99),
            "max": series.max,
        }

    @staticmethod
    def _exemplar_suffix(series: _HistogramSeries, i: int) -> str:
        """OpenMetrics exemplar clause for bucket ``i`` (empty when none):
        ``# {trace_id="…"} <value>`` appended after the bucket sample."""
        entry = series.exemplars.get(i)
        if entry is None:
            return ""
        ex, v = entry
        return f' # {{trace_id="{_escape_label_value(ex)}"}} {format_value(v)}'

    def render(self, exemplars: bool = False) -> list[str]:
        out = []
        for key, _ in self.collect():
            series = self._snapshot_series(key)
            if series is None:
                continue
            acc = 0
            for i, (bound, c) in enumerate(zip(self.buckets, series.counts)):
                acc += c
                names = self.labelnames + ("le",)
                values = key + (format_value(bound),)
                suffix = self._exemplar_suffix(series, i) if exemplars else ""
                out.append(
                    f"{self.name}_bucket{_format_labels(names, values)} "
                    f"{acc}{suffix}"
                )
            names = self.labelnames + ("le",)
            suffix = (
                self._exemplar_suffix(series, len(self.buckets))
                if exemplars
                else ""
            )
            out.append(
                f"{self.name}_bucket{_format_labels(names, key + ('+Inf',))} "
                f"{series.count}{suffix}"
            )
            out.append(
                f"{self.name}_sum{_format_labels(self.labelnames, key)} "
                f"{format_value(series.sum)}"
            )
            out.append(
                f"{self.name}_count{_format_labels(self.labelnames, key)} "
                f"{series.count}"
            )
        return out


class MetricsRegistry:
    """Instrument factory + exposition. Get-or-create semantics so every
    layer (server, batcher, stats collector, compile watcher) can ask for
    the instrument by name without threading object references around;
    re-declaring with a different type or label set is a programming
    error and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], None]] = []

    def _get_or_create(self, cls, name: str, help: str, labelnames, **kw) -> Any:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labelnames: Iterable[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Iterable[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Iterable[str] = (),
        buckets: Iterable[float] | None = None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def register_collector(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` before every scrape/snapshot — the hook lazy gauges
        and the compile watcher use to refresh derived state exactly when
        someone is looking."""
        with self._lock:
            self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass  # a broken collector must never fail the scrape

    def render_prometheus(self, exemplars: bool = False) -> str:
        """Prometheus text exposition format v0.0.4. With ``exemplars=True``
        histogram bucket lines carry OpenMetrics exemplar clauses
        (``… # {trace_id="…"} value``) and the output ends with ``# EOF``
        — serve that variant only to scrapers that negotiated OpenMetrics
        (a strict v0.0.4 parser rejects exemplar syntax)."""
        self._run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            if m.help:
                escaped = m.help.replace("\\", "\\\\").replace("\n", "\\n")
                lines.append(f"# HELP {m.name} {escaped}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render(exemplars=exemplars))
        if exemplars:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict[str, Any]:
        """JSON twin of the exposition: bench output and dashboards."""
        self._run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: dict[str, Any] = {}
        for m in metrics:
            samples: list[dict[str, Any]] = []
            if isinstance(m, Histogram):
                for key, _ in m.collect():
                    labels = dict(zip(m.labelnames, key))
                    samples.append({"labels": labels, **m.summary(**labels)})
            else:
                for key, v in m.collect():
                    if callable(v):
                        try:
                            v = float(v())
                        except Exception:
                            continue
                    samples.append(
                        {"labels": dict(zip(m.labelnames, key)), "value": v}
                    )
            out[m.name] = {"type": m.kind, "samples": samples}
        return out
