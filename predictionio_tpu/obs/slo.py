"""Declarative SLOs evaluated as multi-window burn rates.

The paper's own serving target — ``pio deploy`` answering at <10 ms p50 —
has so far been a bench assertion, not an operational signal. This module
makes objectives first-class: each SLO declares what a *bad* event is
(request over the latency threshold, 5xx answer, shed request) and what
fraction of good events it promises (the objective); the engine then
evaluates **burn rates** over multiple trailing windows from counter
snapshots, the way SRE alerting does it:

    bad_ratio(window) = Δbad / Δtotal          (counter deltas)
    burn_rate(window) = bad_ratio / (1 - objective)

burn 1.0 = consuming error budget exactly at the allowed rate; burn 10 =
the budget is gone 10x too fast. An SLO is *alerting* when every window
of its (short, long) pair exceeds its threshold — the standard
multi-window guard against paging on a single bad scrape.

Sources are cheap callables returning cumulative ``(total, bad)`` read
from the existing registry instruments (no second bookkeeping path):
:func:`counter_ratio_source` splits a labeled counter by a bad-label
predicate, :func:`histogram_threshold_source` counts observations above a
bucket bound (which is why the SLO latency threshold should sit exactly
on a bucket boundary — 10 ms does, on the default ladder), and
:func:`paired_counter_source` rates one counter against another (shed
requests vs all requests).

Snapshots ride on the registry collector hook, i.e. window resolution is
scrape cadence — exactly the resolution Prometheus itself would have.
Exposed three ways: ``pio_slo_*`` gauges on ``/metrics``, the ``/slo``
JSON report, and the `pio top` SLO line. Stdlib-only.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable

from predictionio_tpu.obs.metrics import Counter, Histogram, MetricsRegistry

# (window_seconds, alerting burn threshold): the classic fast/slow pair —
# the fast window catches a cliff, the slow window proves it's sustained;
# both must breach before `alerting` flips.
DEFAULT_WINDOWS: tuple[tuple[float, float], ...] = ((300.0, 14.4), (3600.0, 6.0))

# counter snapshots closer together than this are coalesced: burn math
# needs window-scale resolution, not per-scrape resolution
_MIN_SAMPLE_INTERVAL_S = 0.5

Source = Callable[[], tuple[float, float]]  # -> cumulative (total, bad)


def counter_ratio_source(
    counter: Counter,
    bad: Callable[[dict[str, str]], bool],
    match: Callable[[dict[str, str]], bool] | None = None,
) -> Source:
    """(total, bad) over a labeled counter: ``match`` selects the series
    that count at all (default: every series), ``bad`` the failing ones."""

    def source() -> tuple[float, float]:
        total = 0.0
        bad_total = 0.0
        for key, value in counter.collect():
            labels = dict(zip(counter.labelnames, key))
            if match is not None and not match(labels):
                continue
            total += value
            if bad(labels):
                bad_total += value
        return total, bad_total

    return source


def histogram_threshold_source(
    hist: Histogram, threshold_s: float, **labels: str
) -> Source:
    """(total, over-threshold) from a histogram's cumulative buckets.

    ``threshold_s`` should sit on a bucket bound; when it falls inside a
    bucket the whole bucket counts as good (the conservative direction
    for a latency objective is arguable either way — sitting on a bound
    makes the question moot, which is why the ladder carries 0.01).
    """
    i = bisect.bisect_right(hist.buckets, threshold_s)

    def source() -> tuple[float, float]:
        counts = hist.bucket_counts(**labels)
        total = float(sum(counts))
        return total, total - float(sum(counts[:i]))

    return source


def paired_counter_source(total_fn: Source, bad_counter: Counter) -> Source:
    """Rate one counter against another's total — e.g. shed requests
    (their own counter) against all requests."""

    def source() -> tuple[float, float]:
        total, _ = total_fn()
        return total, bad_counter.total()

    return source


@dataclasses.dataclass
class _Sample:
    t: float
    total: float
    bad: float


class _Objective:
    def __init__(
        self,
        name: str,
        description: str,
        objective: float,
        source: Source,
        windows: tuple[tuple[float, float], ...],
    ):
        if not 0.0 <= objective < 1.0:
            raise ValueError(
                f"objective must be in [0, 1) (got {objective}): an "
                f"objective of 1.0 has zero error budget and an infinite "
                f"burn rate on the first bad event"
            )
        self.name = name
        self.description = description
        self.objective = objective
        self.budget = 1.0 - objective
        self.source = source
        # burn rate is bounded above by 1/budget (every event bad), so the
        # SRE-default thresholds (14.4/6) are unreachable for loose
        # objectives — a p50-style objective of 0.50 caps burn at 2.0 and
        # would structurally never alert. Clamp each window's threshold to
        # 90% of the ceiling so every declared objective stays alertable.
        burn_ceiling = 1.0 / self.budget
        self.windows = tuple(
            (w, min(max_burn, 0.9 * burn_ceiling)) for w, max_burn in windows
        )
        # samples arrive at scrape cadence; rate-limit recording and size
        # the deque from the slowest window so aggressive pollers (several
        # concurrent `pio top` watchers + a scraper) can never evict
        # samples still inside the window and silently shrink its span
        self._horizon_s = max(w for w, _ in self.windows) * 1.25
        maxlen = int(self._horizon_s / _MIN_SAMPLE_INTERVAL_S) + 16
        self.samples: deque[_Sample] = deque(maxlen=maxlen)

    def record(self, now: float) -> None:
        if self.samples and now - self.samples[-1].t < _MIN_SAMPLE_INTERVAL_S:
            return  # coalesce scrape bursts; windows keep full span
        total, bad = self.source()
        self.samples.append(_Sample(now, float(total), float(bad)))
        horizon = now - self._horizon_s
        while len(self.samples) > 2 and self.samples[0].t < horizon:
            self.samples.popleft()

    def evaluate(self, now: float) -> dict[str, Any]:
        latest = self.samples[-1] if self.samples else _Sample(now, 0.0, 0.0)
        windows: list[dict[str, Any]] = []
        breaches = 0
        evaluable = 0
        for window_s, max_burn in self.windows:
            base = None
            for s in self.samples:  # oldest sample still inside the window
                if s.t >= now - window_s:
                    base = s
                    break
            if base is None or base is latest:
                windows.append(
                    {
                        "window_s": window_s,
                        "actual_window_s": 0.0,
                        "total": 0.0,
                        "bad": 0.0,
                        "bad_ratio": 0.0,
                        "burn_rate": 0.0,
                        "max_burn": max_burn,
                    }
                )
                continue
            evaluable += 1
            d_total = max(0.0, latest.total - base.total)
            d_bad = max(0.0, latest.bad - base.bad)
            ratio = (d_bad / d_total) if d_total > 0 else 0.0
            burn = ratio / self.budget
            if burn > max_burn:
                breaches += 1
            windows.append(
                {
                    "window_s": window_s,
                    "actual_window_s": round(latest.t - base.t, 3),
                    "total": d_total,
                    "bad": d_bad,
                    "bad_ratio": round(ratio, 6),
                    "burn_rate": round(burn, 4),
                    "max_burn": max_burn,
                }
            )
        # multi-window rule: every evaluable window must breach; no data
        # is "not alerting", not "unknown-so-page"
        alerting = evaluable == len(self.windows) and breaches == len(self.windows)
        slow = windows[-1] if windows else None
        budget_remaining = (
            max(0.0, 1.0 - slow["bad_ratio"] / self.budget) if slow else 1.0
        )
        return {
            "name": self.name,
            "description": self.description,
            "objective": self.objective,
            "windows": windows,
            "alerting": alerting,
            "budget_remaining": round(budget_remaining, 4),
        }


class SLOEngine:
    """Objective registry + evaluator + gauge exporter.

    Construct with the server's metrics registry, ``add(...)`` each
    objective, then ``registry.register_collector(engine.collect)`` so
    every scrape snapshots the counters and refreshes the ``pio_slo_*``
    gauges. ``report(now=...)`` is the JSON twin behind ``/slo``.
    """

    def __init__(self, registry: MetricsRegistry):
        self._lock = threading.Lock()
        self._objectives: list[_Objective] = []
        self._g_burn = registry.gauge(
            "pio_slo_burn_rate",
            "error-budget burn rate per SLO and trailing window "
            "(1.0 = consuming budget exactly at the allowed rate)",
            labelnames=("slo", "window"),
        )
        self._g_bad = registry.gauge(
            "pio_slo_bad_ratio",
            "bad-event fraction per SLO and trailing window",
            labelnames=("slo", "window"),
        )
        self._g_alerting = registry.gauge(
            "pio_slo_alerting",
            "1 when every window of the SLO's multi-window pair exceeds "
            "its burn threshold",
            labelnames=("slo",),
        )
        self._g_objective = registry.gauge(
            "pio_slo_objective",
            "declared good-event objective per SLO",
            labelnames=("slo",),
        )

    def add(
        self,
        name: str,
        description: str,
        objective: float,
        source: Source,
        windows: tuple[tuple[float, float], ...] = DEFAULT_WINDOWS,
    ) -> None:
        with self._lock:
            if any(o.name == name for o in self._objectives):
                raise ValueError(f"duplicate SLO {name!r}")
            self._objectives.append(
                _Objective(name, description, objective, source, windows)
            )
        self._g_objective.set(objective, slo=name)

    def tick(self, now: float | None = None) -> None:
        """Snapshot every objective's counters (monotonic clock — burn
        windows must never jump with a wall-clock step)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            objectives = list(self._objectives)
        for obj in objectives:
            try:
                obj.record(now)
            except Exception:
                pass  # a broken source must not break the scrape

    def evaluate(self, now: float | None = None) -> list[dict[str, Any]]:
        now = time.monotonic() if now is None else now
        with self._lock:
            objectives = list(self._objectives)
        out = []
        for obj in objectives:
            report = obj.evaluate(now)
            for w in report["windows"]:
                label = str(int(w["window_s"]))
                self._g_burn.set(w["burn_rate"], slo=obj.name, window=label)
                self._g_bad.set(w["bad_ratio"], slo=obj.name, window=label)
            self._g_alerting.set(
                1.0 if report["alerting"] else 0.0, slo=obj.name
            )
            out.append(report)
        return out

    def collect(self) -> None:
        """Registry collector hook: one tick + gauge refresh per scrape."""
        self.tick()
        self.evaluate()

    def report(self, now: float | None = None) -> dict[str, Any]:
        """The ``/slo`` JSON body."""
        self.tick(now)
        return {"slos": self.evaluate(now)}


__all__ = [
    "DEFAULT_WINDOWS",
    "SLOEngine",
    "counter_ratio_source",
    "histogram_threshold_source",
    "paired_counter_source",
]
