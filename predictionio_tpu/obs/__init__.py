"""Observability layer: metrics registry, request tracing, XLA profiling.

One vocabulary for everything the serving/storage/training stack needs to
be *operable* at fleet scale (see ``docs/observability.md``):

- :mod:`predictionio_tpu.obs.metrics` — lock-cheap counters/gauges/
  fixed-bucket histograms with p50/p95/p99 extraction, exported in
  Prometheus text format from ``/metrics`` on both servers.
- :mod:`predictionio_tpu.obs.tracing` — request-scoped trace ids
  (minted or accepted via ``X-Pio-Trace-Id``) propagated through the
  micro-batcher, engine dispatch, and storage DAO calls; spans land in a
  ring buffer (``/traces/recent``) and as JSON lines on ``pio.trace``.
- :mod:`predictionio_tpu.obs.jaxprof` — jit cache-miss accounting
  (recompile storms become a gauge + warning), XLA compile event taps,
  and ``block_until_ready`` stall accounting.
- :mod:`predictionio_tpu.obs.waterfall` — per-request latency
  attribution: every query accounted into an explicit phase waterfall
  (``pio_phase_seconds{phase=…}``) with trace-id exemplars per bucket.
- :mod:`predictionio_tpu.obs.slo` — declarative objectives (latency,
  availability, shed rate) evaluated as multi-window burn rates from
  registry counter snapshots; ``/slo`` + ``pio_slo_*`` gauges.
- :mod:`predictionio_tpu.obs.xray` — training observability: the
  per-iteration step profiler (``pio_train_*`` metrics, ``train.step``
  spans, profiles attached to registry manifests), the HBM capacity
  planner behind ``pio doctor --capacity``, and the sharding inspector.
- :mod:`predictionio_tpu.obs.profiler` — on-demand XLA device-trace
  capture: single-flight, duration-bounded, published as
  content-addressed profile bundles (``POST /profile/capture``,
  ``pio profile``); absorbs the ``PIO_PROFILE_DIR`` training gate.
- :mod:`predictionio_tpu.obs.sampler` — always-on host stack sampler
  with thread-role attribution and folded-stack output
  (``GET /profile/stacks``, ``pio top --hotspots``), self-measured to
  stay under 1% CPU.
- :mod:`predictionio_tpu.obs.costmodel` — device-free roofline from
  ``compiled.cost_analysis()`` flops/bytes per registered jit bucket
  (``pio doctor --roofline``, ``roofline_*`` bench fields).

``metrics``, ``tracing``, ``waterfall``, ``slo``, and ``sampler`` are
stdlib-only; ``jaxprof``, ``xray``, ``profiler``, and ``costmodel``
import jax lazily — so the event server, ``pio top``, and the lint CLI
can use this package without dragging in an accelerator runtime.
"""

from predictionio_tpu.obs.jaxprof import (
    CompileWatcher,
    install_jax_monitoring,
    timed_block_until_ready,
)
from predictionio_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from predictionio_tpu.obs.slo import (
    SLOEngine,
    counter_ratio_source,
    histogram_threshold_source,
    paired_counter_source,
)
from predictionio_tpu.obs.profiler import (
    ProfileBusyError,
    ProfileSession,
    ProfileStore,
    maybe_profile_train,
)
from predictionio_tpu.obs.sampler import HostSampler
from predictionio_tpu.obs.waterfall import PHASES, PhaseWaterfall, phase_tags_ms
from predictionio_tpu.obs import costmodel, xray
from predictionio_tpu.obs.tracing import (
    TRACE_HEADER,
    Span,
    Tracer,
    current_trace_id,
    get_trace_logger,
    get_tracer,
    mint_trace_id,
    reset_trace_id,
    set_trace_id,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "PHASES",
    "TRACE_HEADER",
    "CompileWatcher",
    "Counter",
    "Gauge",
    "Histogram",
    "HostSampler",
    "MetricsRegistry",
    "PhaseWaterfall",
    "ProfileBusyError",
    "ProfileSession",
    "ProfileStore",
    "SLOEngine",
    "Span",
    "Tracer",
    "costmodel",
    "counter_ratio_source",
    "histogram_threshold_source",
    "maybe_profile_train",
    "paired_counter_source",
    "phase_tags_ms",
    "current_trace_id",
    "get_trace_logger",
    "get_tracer",
    "install_jax_monitoring",
    "mint_trace_id",
    "reset_trace_id",
    "set_trace_id",
    "timed_block_until_ready",
    "xray",
]
