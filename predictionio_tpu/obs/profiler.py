"""On-demand device trace capture into content-addressed profile bundles.

``obs/xray`` tells you the *shape* of a train (phases, steps, memory) and
``obs/waterfall`` the shape of a query — this module captures the ground
truth underneath both: the XLA device trace (``jax.profiler.start_trace``
/ ``stop_trace``), bounded in duration and published as a **profile
bundle** with the same content-addressed layout as the incident flight
recorder (``obs/incidents``): JSON parts + raw texts + a ``trace/``
subtree of device artifacts under ``<dir>/<utc-stamp>-<sha12>/``, written
tmp+rename so a half-capture is never mistaken for a whole one, GC'd to
the newest ``max_bundles``.

The manifest carries what a trace viewer cannot: the trigger, the engine
and model version that was serving, the registry generation, and the
phase-waterfall snapshot at capture time — so a trace pulled off a 3am
incident still says *which* model produced it. Because the layout is the
incident layout, ``list_bundles``/``load_bundle``/``export_bundle`` from
``obs/incidents`` work unchanged; ``pio profile list|show|export`` are
thin wrappers over them.

Capture is **single-flight**: ``jax.profiler`` keeps one global trace
session per process, so a second concurrent ``POST /profile/capture``
gets :class:`ProfileBusyError` (HTTP 409), never a corrupted trace.
Everything here is blocking by design — the HTTP handlers hand capture
to ``run_in_executor`` (held by the async-blocking lint family, which
names this module an entry point).

``PIO_PROFILE_DIR`` compatibility: :func:`maybe_profile_train` replaces
the old ``_maybe_profile`` wrapper in ``workflow/core_workflow`` — same
env gate, but the trace now lands as a content-addressed bundle (with
manifest + GC) instead of a bare artifact directory, cross-linking the
xray TrainProfile trainer when one is active.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Callable

from predictionio_tpu.obs.incidents import (
    MANIFEST_NAME,
    BundleRef,
    _jsonable,
    export_bundle,
    list_bundles,
    load_bundle,
)

logger = logging.getLogger(__name__)

PROFILE_DIR_ENV = "PIO_PROFILE_DIR"

# duration rails for HTTP-triggered captures: the trace buffers device
# events in memory and writes multi-MB artifacts, so an unbounded ms
# parameter is a self-DoS — clamp, don't trust
DEFAULT_CAPTURE_MS = 500
MAX_CAPTURE_MS = 10_000


class ProfileBusyError(RuntimeError):
    """A capture is already in flight (jax keeps ONE global trace
    session per process); surfaces as HTTP 409."""


class ProfileStore:
    """Content-addressed profile bundles under one directory.

    Same bundle grammar as :class:`obs.incidents.IncidentRecorder` plus a
    ``trace/`` subtree for the raw XLA artifacts; the manifest inventories
    every trace file (name, bytes, sha256) so ``pio profile show`` can
    verify what it prints without parsing protobufs.
    """

    def __init__(self, dir_path: str, max_bundles: int = 20):
        self.dir = dir_path
        self.max_bundles = int(max_bundles)

    def ensure_dir(self) -> str:
        """Lazy creation: constructing a server must not scatter empty
        obs directories; the first capture makes it."""
        os.makedirs(self.dir, exist_ok=True)
        return self.dir

    # -------------------------------------------------------------- publish
    def publish(
        self,
        trigger: str,
        context: dict[str, Any] | None = None,
        parts: dict[str, Any] | None = None,
        texts: dict[str, str] | None = None,
        trace_dir: str | None = None,
    ) -> str:
        """Write one bundle; returns its path. ``trace_dir`` (the raw
        ``jax.profiler`` output tree) is *moved* into the bundle's
        ``trace/`` subtree. Blocking file I/O — callers on an event loop
        must hand this to an executor."""
        self.ensure_dir()
        captured_at = time.time()
        parts = {k: _jsonable(v) for k, v in (parts or {}).items()}
        texts = dict(texts or {})
        trace_files = self._trace_inventory(trace_dir)
        manifest: dict[str, Any] = {
            "trigger": trigger,
            "capturedAt": captured_at,
            "capturedAtMonotonic": time.monotonic(),
            "context": _jsonable(context or {}),
            "parts": sorted(parts),
            "texts": sorted(texts),
            "trace": trace_files,
        }
        hasher = hashlib.sha256()
        hasher.update(json.dumps(manifest, sort_keys=True).encode())
        for name in sorted(parts):
            hasher.update(json.dumps(parts[name], sort_keys=True).encode())
        for name in sorted(texts):
            hasher.update(texts[name].encode("utf-8", errors="replace"))
        digest = hasher.hexdigest()
        manifest["sha256"] = digest
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(captured_at))
        bundle_id = f"{stamp}-{digest[:12]}"
        final = os.path.join(self.dir, bundle_id)
        tmp = os.path.join(self.dir, f".tmp-{bundle_id}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        try:
            for name, value in parts.items():
                with open(
                    os.path.join(tmp, f"{name}.json"), "w", encoding="utf-8"
                ) as fh:
                    json.dump(value, fh, indent=2, sort_keys=True)
            for name, text in texts.items():
                with open(
                    os.path.join(tmp, f"{name}.txt"),
                    "w",
                    encoding="utf-8",
                    errors="replace",
                ) as fh:
                    fh.write(text)
            if trace_dir is not None and os.path.isdir(trace_dir):
                shutil.move(trace_dir, os.path.join(tmp, "trace"))
            with open(
                os.path.join(tmp, MANIFEST_NAME), "w", encoding="utf-8"
            ) as fh:
                json.dump(manifest, fh, indent=2, sort_keys=True)
            if os.path.isdir(final):
                shutil.rmtree(tmp)  # identical evidence already captured
            else:
                os.rename(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        logger.info("profile bundle published: %s (%s)", bundle_id, trigger)
        return final

    @staticmethod
    def _trace_inventory(trace_dir: str | None) -> list[dict[str, Any]]:
        if trace_dir is None or not os.path.isdir(trace_dir):
            return []
        inventory: list[dict[str, Any]] = []
        for root, _dirs, files in os.walk(trace_dir):
            for name in files:
                path = os.path.join(root, name)
                rel = os.path.relpath(path, trace_dir)
                h = hashlib.sha256()
                try:
                    with open(path, "rb") as fh:
                        for chunk in iter(lambda: fh.read(1 << 20), b""):
                            h.update(chunk)
                    size = os.path.getsize(path)
                except OSError:
                    continue
                inventory.append(
                    {"name": rel, "bytes": size, "sha256": h.hexdigest()}
                )
        inventory.sort(key=lambda e: e["name"])
        return inventory

    def _gc(self) -> None:
        refs = list_bundles(self.dir)
        for ref in refs[: max(0, len(refs) - self.max_bundles)]:
            shutil.rmtree(ref.path, ignore_errors=True)

    # ------------------------------------------------------------ inspection
    def list(self) -> list[BundleRef]:
        return list_bundles(self.dir)

    def load(self, bundle_id: str) -> dict[str, Any]:
        return load_bundle(self.dir, bundle_id)

    def export(self, bundle_id: str, dest: str) -> str:
        return export_bundle(self.dir, bundle_id, dest)


class ProfileSession:
    """Single-flight device-trace capture publishing into a store.

    One session per server process; ``capture()`` raises
    :class:`ProfileBusyError` when a capture is already running. Alert
    paths use ``capture_alert()`` — rate-limited per trigger kind (a
    breaker flapping at dispatch rate must produce a few bundles, not
    thousands) and never raising.
    """

    def __init__(
        self,
        store: ProfileStore,
        *,
        default_ms: int = DEFAULT_CAPTURE_MS,
        max_ms: int = MAX_CAPTURE_MS,
        alert_min_interval_s: float = 60.0,
        alert_trace_ms: int = 0,
        context_fn: Callable[[], dict[str, Any]] | None = None,
        metrics: Any | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.store = store
        self.default_ms = int(default_ms)
        self.max_ms = int(max_ms)
        self.alert_min_interval_s = float(alert_min_interval_s)
        self.alert_trace_ms = int(alert_trace_ms)
        # manifest enrichment (engine/model version, registry generation,
        # waterfall snapshot) supplied by the owning server at capture time
        self.context_fn = context_fn
        self._clock = clock
        self._flight = threading.Lock()
        self._last_alert: dict[str, float] = {}
        self._alert_lock = threading.Lock()
        if metrics is not None:
            self._m_captures = metrics.counter(
                "pio_profile_captures_total",
                "profile bundles captured, by trigger kind (manual / "
                "slo-alert / breaker-trip / train)",
                labelnames=("trigger",),
            )
            self._m_busy = metrics.counter(
                "pio_profile_capture_busy_total",
                "capture requests rejected because one was already in "
                "flight (the single-flight rail; HTTP 409)",
            )
            self._m_errors = metrics.counter(
                "pio_profile_capture_errors_total",
                "captures that failed (tracer unavailable, publish error)",
            )
            self._m_last_ms = metrics.gauge(
                "pio_profile_last_capture_ms",
                "requested duration of the most recent device capture",
            )
            self._m_bundles = metrics.gauge(
                "pio_profile_bundles",
                "profile bundles currently on disk in this server's store",
            )
            self._m_bundles.set_function(lambda: float(len(self.store.list())))
        else:
            self._m_captures = self._m_busy = None
            self._m_errors = self._m_last_ms = None

    def clamp_ms(self, ms: int | None) -> int:
        if ms is None:
            return self.default_ms
        return max(0, min(int(ms), self.max_ms))

    def _base_context(self) -> dict[str, Any]:
        if self.context_fn is None:
            return {}
        try:
            return dict(self.context_fn())
        except Exception as exc:  # noqa: BLE001 - context must not sink capture
            return {"contextError": f"{type(exc).__name__}: {exc}"}

    # -------------------------------------------------------------- capture
    def capture(
        self,
        ms: int | None = None,
        trigger: str = "manual",
        context: dict[str, Any] | None = None,
        parts: dict[str, Any] | None = None,
        texts: dict[str, str] | None = None,
    ) -> str:
        """Capture a bounded device trace (``ms`` clamped to
        ``[0, max_ms]``; 0 skips the device trace and publishes a
        host-only bundle) and publish it. Blocking — run on an executor.
        Raises :class:`ProfileBusyError` when a capture is in flight."""
        if not self._flight.acquire(blocking=False):
            if self._m_busy is not None:
                self._m_busy.inc()
            raise ProfileBusyError("a profile capture is already in flight")
        try:
            duration_ms = self.clamp_ms(ms)
            ctx = {**self._base_context(), **(context or {})}
            ctx["durationMs"] = duration_ms
            trace_dir: str | None = None
            if duration_ms > 0:
                trace_dir = tempfile.mkdtemp(
                    prefix=".trace-", dir=self.store.ensure_dir()
                )
                import jax

                jax.profiler.start_trace(trace_dir)
                try:
                    time.sleep(duration_ms / 1000.0)
                finally:
                    jax.profiler.stop_trace()
            path = self.store.publish(
                trigger,
                context=ctx,
                parts=parts,
                texts=texts,
                trace_dir=trace_dir,
            )
            if self._m_captures is not None:
                self._m_captures.inc(trigger=trigger)
                self._m_last_ms.set(float(duration_ms))
            return path
        except ProfileBusyError:
            raise
        except Exception:
            if self._m_errors is not None:
                self._m_errors.inc()
            raise
        finally:
            self._flight.release()

    def capture_alert(
        self,
        trigger: str,
        context: dict[str, Any] | None = None,
        parts: dict[str, Any] | None = None,
        texts: dict[str, str] | None = None,
    ) -> str | None:
        """The profile-on-alert entry: rate-limited per trigger kind,
        never raises (a broken profiler must not take down the failure
        path that called it). Device trace only when ``alert_trace_ms``
        > 0 — the host-stack snapshot in ``parts``/``texts`` is the
        always-available evidence."""
        now = self._clock()
        with self._alert_lock:
            last = self._last_alert.get(trigger)
            if last is not None and now - last < self.alert_min_interval_s:
                return None
            self._last_alert[trigger] = now
        try:
            return self.capture(
                ms=self.alert_trace_ms,
                trigger=trigger,
                context=context,
                parts=parts,
                texts=texts,
            )
        except ProfileBusyError:
            return None
        except Exception:
            logger.exception("profile-on-alert capture failed (%s)", trigger)
            return None

    @contextlib.contextmanager
    def trace(
        self,
        trigger: str = "train",
        context: dict[str, Any] | None = None,
        parts_fn: Callable[[], dict[str, Any]] | None = None,
    ):
        """Single-flight device trace around a long-running body (a
        train): unbounded by ``max_ms`` — the body's wall clock *is* the
        duration. Yields a result box whose ``"path"`` key holds the
        bundle path after exit; ``parts_fn`` is called at exit so the
        bundle can embed state that only exists once the body ran (the
        xray TrainProfile cross-link)."""
        if not self._flight.acquire(blocking=False):
            if self._m_busy is not None:
                self._m_busy.inc()
            raise ProfileBusyError("a profile capture is already in flight")
        box: dict[str, Any] = {}
        try:
            trace_dir = tempfile.mkdtemp(
                prefix=".trace-", dir=self.store.ensure_dir()
            )
            import jax

            t0 = time.perf_counter()
            jax.profiler.start_trace(trace_dir)
            try:
                yield box
            finally:
                jax.profiler.stop_trace()
            wall_ms = int((time.perf_counter() - t0) * 1000.0)
            parts = dict(parts_fn() if parts_fn is not None else {})
            ctx = {**self._base_context(), **(context or {})}
            ctx["durationMs"] = wall_ms
            box["path"] = self.store.publish(
                trigger, context=ctx, parts=parts, trace_dir=trace_dir
            )
            if self._m_captures is not None:
                self._m_captures.inc(trigger=trigger)
                self._m_last_ms.set(float(wall_ms))
        finally:
            self._flight.release()


@contextlib.contextmanager
def maybe_profile_train(
    context: dict[str, Any] | None = None,
    parts_fn: Callable[[], dict[str, Any]] | None = None,
):
    """``PIO_PROFILE_DIR`` compatibility gate, absorbed from the old
    ``workflow.core_workflow._maybe_profile``: unset -> no-op (yields
    ``None``); set -> the train runs inside a device trace whose
    artifacts land as a content-addressed bundle (manifest + newest-N GC)
    under that directory. Yields the session's result box (``box["path"]``
    after exit) so the caller can log/cross-link the bundle."""
    profile_dir = os.environ.get(PROFILE_DIR_ENV)
    if not profile_dir:
        yield None
        return
    store = ProfileStore(profile_dir)
    session = ProfileSession(store)
    with session.trace(
        trigger="train", context=context, parts_fn=parts_fn
    ) as box:
        yield box
    logger.info(
        "XLA training profile bundle written to %s", box.get("path")
    )


__all__ = [
    "DEFAULT_CAPTURE_MS",
    "MAX_CAPTURE_MS",
    "PROFILE_DIR_ENV",
    "ProfileBusyError",
    "ProfileSession",
    "ProfileStore",
    "maybe_profile_train",
]
