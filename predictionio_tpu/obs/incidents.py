"""Incident flight recorder: snapshot the evidence before it evaporates.

When a worker crashes, a breaker trips, or a fleet SLO starts burning,
the evidence an operator needs — the spans of the affected requests, the
telemetry history leading up to it, the rollout state, the dead
process's stderr — lives in process memory and dies with the process.
This module captures it at trigger time into an **incident bundle**: a
content-addressed directory of JSON parts plus raw text tails, published
atomically (written to a temp dir, ``os.rename``'d into place) so a
half-captured bundle can never be mistaken for a whole one.

Bundle layout (``<dir>/<utc-stamp>-<sha12>/``):

- ``manifest.json`` — trigger kind, wall/monotonic capture times, the
  caller's context dict, the list of captured parts, and the bundle's
  content hash (sha256 over every part, so ``pio incidents show``
  verifies what it prints).
- ``<source>.json`` — one file per registered source callable (merged
  recent traces, telemetry-ring tail, registry/rollout state, supervisor
  restart ladder, ...). A failing source records ``{"error": ...}``
  instead of sinking the capture.
- ``<name>.txt`` — raw text parts (the dead worker's stderr tail).

Triggers are rate-limited per kind (``min_interval_s``) — a crash-loop
must produce a few bundles, not thousands — and the directory is GC'd to
the newest ``max_bundles``. Stdlib-only; the async tiers hand the
recorder *sync* source callables (cached fan-in state), so a trigger
never blocks on the network mid-incident.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import threading
import time
from typing import Any, Callable

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"


@dataclasses.dataclass(frozen=True)
class BundleRef:
    """One on-disk bundle, as ``pio incidents list`` sees it."""

    bundle_id: str
    path: str
    trigger: str
    captured_at: float

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "id": self.bundle_id,
            "path": self.path,
            "trigger": self.trigger,
            "capturedAt": self.captured_at,
        }


class IncidentRecorder:
    """Named sources + rate-limited triggers -> content-addressed bundles.

    Construct once per fleet parent, ``add_source(name, fn)`` for every
    evidence stream (each ``fn`` is a cheap sync callable returning a
    JSON-serializable value), then call :meth:`trigger` from the failure
    paths. The clock is injectable so rate-limiting unit-tests without
    sleeping.
    """

    def __init__(
        self,
        dir_path: str,
        metrics: Any | None = None,
        min_interval_s: float = 30.0,
        max_bundles: int = 50,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.dir = dir_path
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self._clock = clock
        self._lock = threading.Lock()
        self._sources: dict[str, Callable[[], Any]] = {}
        self._last_trigger: dict[str, float] = {}
        os.makedirs(self.dir, exist_ok=True)
        if metrics is not None:
            self._m_bundles = metrics.counter(
                "pio_incident_bundles_total",
                "incident bundles captured, by trigger kind",
                labelnames=("trigger",),
            )
            self._m_suppressed = metrics.counter(
                "pio_incident_suppressed_total",
                "incident triggers suppressed by per-kind rate limiting",
            )
            self._m_errors = metrics.counter(
                "pio_incident_capture_errors_total",
                "evidence sources that failed during a bundle capture",
            )
        else:
            self._m_bundles = self._m_suppressed = self._m_errors = None

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        """Register an evidence stream; captured into ``<name>.json`` on
        every trigger. Re-registering a name replaces it."""
        with self._lock:
            self._sources[name] = fn

    # ------------------------------------------------------------- capture
    def trigger(
        self,
        kind: str,
        context: dict[str, Any] | None = None,
        texts: dict[str, str] | None = None,
    ) -> str | None:
        """Capture a bundle for one incident. Returns the bundle path, or
        ``None`` when rate-limited. ``context`` rides in the manifest
        (who/what/where at trigger time); ``texts`` become raw ``.txt``
        parts (stderr tails). Never raises — a broken recorder must not
        take down the failure path that called it."""
        now = self._clock()
        with self._lock:
            last = self._last_trigger.get(kind)
            if last is not None and now - last < self.min_interval_s:
                if self._m_suppressed is not None:
                    self._m_suppressed.inc()
                return None
            self._last_trigger[kind] = now
            sources = dict(self._sources)
        try:
            return self._capture(kind, context or {}, texts or {}, sources)
        except Exception:
            logger.exception("incident capture failed for trigger %r", kind)
            if self._m_errors is not None:
                self._m_errors.inc()
            return None

    def _capture(
        self,
        kind: str,
        context: dict[str, Any],
        texts: dict[str, str],
        sources: dict[str, Callable[[], Any]],
    ) -> str:
        captured_at = time.time()
        parts: dict[str, Any] = {}
        for name, fn in sorted(sources.items()):
            try:
                parts[name] = _jsonable(fn())
            except Exception as exc:
                parts[name] = {"error": f"{type(exc).__name__}: {exc}"}
                if self._m_errors is not None:
                    self._m_errors.inc()
        manifest = {
            "trigger": kind,
            "capturedAt": captured_at,
            "capturedAtMonotonic": self._clock(),
            "context": _jsonable(context),
            "parts": sorted(parts),
            "texts": sorted(texts),
        }
        # content address: sha256 over the canonical serialization of
        # everything captured — identical evidence dedupes to one id and
        # `pio incidents show` can verify the bundle it prints
        hasher = hashlib.sha256()
        hasher.update(json.dumps(manifest, sort_keys=True).encode())
        for name in sorted(parts):
            hasher.update(json.dumps(parts[name], sort_keys=True).encode())
        for name in sorted(texts):
            hasher.update(texts[name].encode("utf-8", errors="replace"))
        digest = hasher.hexdigest()
        manifest["sha256"] = digest
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(captured_at))
        bundle_id = f"{stamp}-{digest[:12]}"
        final = os.path.join(self.dir, bundle_id)
        tmp = os.path.join(self.dir, f".tmp-{bundle_id}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        try:
            for name, value in parts.items():
                with open(
                    os.path.join(tmp, f"{name}.json"), "w", encoding="utf-8"
                ) as fh:
                    json.dump(value, fh, indent=2, sort_keys=True)
            for name, text in texts.items():
                with open(
                    os.path.join(tmp, f"{name}.txt"),
                    "w",
                    encoding="utf-8",
                    errors="replace",
                ) as fh:
                    fh.write(text)
            with open(
                os.path.join(tmp, MANIFEST_NAME), "w", encoding="utf-8"
            ) as fh:
                json.dump(manifest, fh, indent=2, sort_keys=True)
            if os.path.isdir(final):
                shutil.rmtree(tmp)  # identical evidence already captured
            else:
                os.rename(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if self._m_bundles is not None:
            self._m_bundles.inc(trigger=kind)
        self._gc()
        logger.warning("incident bundle captured: %s (%s)", bundle_id, kind)
        return final

    def _gc(self) -> None:
        refs = list_bundles(self.dir)
        for ref in refs[: max(0, len(refs) - self.max_bundles)]:
            shutil.rmtree(ref.path, ignore_errors=True)


def _jsonable(value: Any) -> Any:
    """Best-effort JSON coercion: evidence capture must never die on a
    numpy scalar or dataclass riding in a snapshot."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return json.loads(json.dumps(value, default=repr))


# --------------------------------------------------------------- inspection
def list_bundles(dir_path: str) -> list[BundleRef]:
    """Bundles oldest first (the `pio incidents list` order; GC drops
    from the front). Unreadable entries are skipped, not fatal."""
    refs: list[BundleRef] = []
    try:
        names = os.listdir(dir_path)
    except OSError:
        return []
    for name in names:
        if name.startswith("."):
            continue
        path = os.path.join(dir_path, name)
        manifest_path = os.path.join(path, MANIFEST_NAME)
        if not os.path.isfile(manifest_path):
            continue
        try:
            with open(manifest_path, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        refs.append(
            BundleRef(
                bundle_id=name,
                path=path,
                trigger=str(manifest.get("trigger", "?")),
                captured_at=float(manifest.get("capturedAt", 0.0)),
            )
        )
    refs.sort(key=lambda r: (r.captured_at, r.bundle_id))
    return refs


def load_bundle(dir_path: str, bundle_id: str) -> dict[str, Any]:
    """The whole bundle as one dict: manifest + every part + every text.
    ``bundle_id`` may be a unique prefix (like git short hashes)."""
    matches = [
        r for r in list_bundles(dir_path) if r.bundle_id.startswith(bundle_id)
    ]
    if not matches:
        raise FileNotFoundError(f"no incident bundle matching {bundle_id!r}")
    if len(matches) > 1:
        ids = ", ".join(r.bundle_id for r in matches)
        raise ValueError(f"ambiguous bundle id {bundle_id!r}: {ids}")
    path = matches[0].path
    with open(os.path.join(path, MANIFEST_NAME), encoding="utf-8") as fh:
        manifest = json.load(fh)
    out: dict[str, Any] = {"manifest": manifest, "parts": {}, "texts": {}}
    for name in manifest.get("parts", []):
        try:
            with open(
                os.path.join(path, f"{name}.json"), encoding="utf-8"
            ) as fh:
                out["parts"][name] = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            out["parts"][name] = {"error": f"unreadable: {exc}"}
    for name in manifest.get("texts", []):
        try:
            with open(
                os.path.join(path, f"{name}.txt"),
                encoding="utf-8",
                errors="replace",
            ) as fh:
                out["texts"][name] = fh.read()
        except OSError as exc:
            out["texts"][name] = f"unreadable: {exc}"
    return out


def export_bundle(dir_path: str, bundle_id: str, dest: str) -> str:
    """Copy one bundle directory to ``dest`` (for attaching to a ticket);
    returns the created path."""
    matches = [
        r for r in list_bundles(dir_path) if r.bundle_id.startswith(bundle_id)
    ]
    if not matches:
        raise FileNotFoundError(f"no incident bundle matching {bundle_id!r}")
    if len(matches) > 1:
        ids = ", ".join(r.bundle_id for r in matches)
        raise ValueError(f"ambiguous bundle id {bundle_id!r}: {ids}")
    src = matches[0].path
    target = os.path.join(dest, matches[0].bundle_id)
    shutil.copytree(src, target)
    return target


__all__ = [
    "BundleRef",
    "IncidentRecorder",
    "export_bundle",
    "list_bundles",
    "load_bundle",
]
