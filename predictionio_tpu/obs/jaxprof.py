"""JAX compile/dispatch profiling hooks.

TPU serving systems die of invisible compiles: a ragged request shape
slips past the pow2 buckets, every arrival compiles a fresh XLA program
(a full round-trip on a tunneled chip), and the operator sees only a p99
cliff. This module makes that failure mode a first-class signal:

- :class:`CompileWatcher` tracks the jit cache size of every compiled
  function in the package (``PjitFunction._cache_size``); growth between
  samples becomes ``pio_jit_cache_misses_total{fn=...}`` and a burst
  above ``storm_threshold`` in one sampling interval raises the
  ``pio_jit_recompile_storm`` gauge and logs a warning naming the
  functions that recompiled.
- :func:`install_jax_monitoring` taps ``jax.monitoring`` (when present)
  for backend compile events and their durations —
  ``pio_xla_compile_events_total`` / ``pio_xla_compile_seconds_total``.
- :func:`timed_block_until_ready` is the sanctioned way for algorithm
  code to host-sync: it accounts the stall into
  ``pio_device_stall_seconds_total`` instead of losing it.

jax itself is imported lazily — constructing a watcher costs nothing on
processes (event server, ``pio top``) that never touch a device.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from typing import Any, Callable

from predictionio_tpu.obs.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# jax.monitoring tap (process-global; registered once, read by any watcher)
# ---------------------------------------------------------------------------

_mon_lock = threading.Lock()
_mon_installed = False
_mon_compile_events = 0
_mon_compile_seconds = 0.0


def _looks_like_compile(event: str) -> bool:
    e = event.lower()
    return "compil" in e or "backend_compile" in e


def _on_event(event: str, *args: Any, **kwargs: Any) -> None:
    global _mon_compile_events
    if _looks_like_compile(str(event)):
        with _mon_lock:
            _mon_compile_events += 1


def _on_duration(event: str, duration_secs: float, *a: Any, **kw: Any) -> None:
    global _mon_compile_seconds
    if _looks_like_compile(str(event)):
        with _mon_lock:
            _mon_compile_seconds += float(duration_secs)


def install_jax_monitoring() -> bool:
    """Register compile-event listeners with ``jax.monitoring``.
    Idempotent; returns False when jax (or the API) is unavailable.
    The whole check-register-set sequence holds the lock (registration
    is a plain list append, never re-enters this module) — a
    check-then-act gap would let two concurrent watchers double-register
    and permanently double-count every compile event."""
    global _mon_installed
    with _mon_lock:
        if _mon_installed:
            return True
        try:
            import jax.monitoring as monitoring

            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        _mon_installed = True
        return True


def monitoring_totals() -> tuple[int, float]:
    with _mon_lock:
        return _mon_compile_events, _mon_compile_seconds


# ---------------------------------------------------------------------------
# compile watcher
# ---------------------------------------------------------------------------


def _is_jitted(obj: Any) -> bool:
    # PjitFunction exposes _cache_size(); duck-typed so we never need to
    # import jax just to scan for compiled functions
    return callable(obj) and callable(getattr(obj, "_cache_size", None))


class CompileWatcher:
    """Samples jit cache sizes and turns growth into metrics.

    ``watch``/``watch_package`` snapshot each function's current cache
    size as its baseline, so compiles that already happened (deploy-time
    warmup — those are *paid for on purpose*) don't count as serving
    recompiles. ``sample()`` is cheap (one C call per watched function)
    and runs as a registry collector, i.e. exactly when someone scrapes
    ``/metrics``.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        storm_threshold: int = 4,
        package_prefix: str = "predictionio_tpu",
    ):
        self.registry = registry
        self.storm_threshold = max(1, storm_threshold)
        self.package_prefix = package_prefix
        self._lock = threading.Lock()
        self._watched: dict[str, Any] = {}
        self._last_size: dict[str, int] = {}
        self._seen_module_count = -1  # rescan trigger (see sample())
        self._misses = registry.counter(
            "pio_jit_cache_misses_total",
            "jit cache misses (recompiles) observed per engine function "
            "since warmup",
            labelnames=("fn",),
        )
        self._cache_size = registry.gauge(
            "pio_jit_cache_size",
            "current jit cache size (compiled program count) per function",
            labelnames=("fn",),
        )
        self._storm = registry.gauge(
            "pio_jit_recompile_storm",
            "recompiles seen in the most recent sampling interval; values "
            ">= the storm threshold also log a warning",
        )
        self._storm.set(0.0)
        self._xla_events = registry.counter(
            "pio_xla_compile_events_total",
            "XLA compile events reported by jax.monitoring",
        )
        self._xla_seconds = registry.counter(
            "pio_xla_compile_seconds_total",
            "cumulative seconds spent in XLA compilation (jax.monitoring)",
        )
        install_jax_monitoring()

    # -- registration -------------------------------------------------------
    def watch(self, name: str, fn: Any) -> bool:
        """Track one compiled function; baseline = its current cache size."""
        if not _is_jitted(fn):
            return False
        try:
            size = int(fn._cache_size())
        except Exception:
            return False
        with self._lock:
            if name not in self._watched:
                self._watched[name] = fn
                self._last_size[name] = size
        return True

    def watch_package(self) -> int:
        """Scan loaded ``<package_prefix>`` modules for module-level jitted
        functions (the framework keeps its serving kernels there — e.g.
        ``ops/als.py``'s top-k programs). Returns how many are watched."""
        for mod_name, module in list(sys.modules.items()):
            if module is None or not mod_name.startswith(self.package_prefix):
                continue
            for attr, value in list(vars(module).items()):
                if _is_jitted(value):
                    self.watch(f"{mod_name.removeprefix(self.package_prefix + '.')}"
                               f".{attr}", value)
        with self._lock:
            return len(self._watched)

    # -- sampling -----------------------------------------------------------
    def sample(self) -> int:
        """Refresh gauges/counters; returns recompiles since last sample.
        Registered as a registry collector so every scrape is current.
        The module scan only re-runs when sys.modules has grown (a lazy
        import may have brought new kernels); the steady-state cost per
        scrape is one ``_cache_size`` read per watched function."""
        n_modules = len(sys.modules)
        if n_modules != self._seen_module_count:
            self.watch_package()
            self._seen_module_count = n_modules
        with self._lock:
            watched = list(self._watched.items())
        new_misses = 0
        stormers: list[str] = []
        for name, fn in watched:
            try:
                size = int(fn._cache_size())
            except Exception:
                continue
            with self._lock:
                last = self._last_size.get(name, size)
                delta = size - last
                self._last_size[name] = size
            self._cache_size.set(size, fn=name)
            if delta > 0:
                self._misses.inc(delta, fn=name)
                new_misses += delta
                stormers.append(f"{name} (+{delta})")
        self._storm.set(float(new_misses))
        if new_misses >= self.storm_threshold:
            logger.warning(
                "recompile storm: %d jit cache misses since last sample: %s",
                new_misses,
                ", ".join(stormers),
            )
        events, seconds = monitoring_totals()
        self._xla_events.set_total(events)
        self._xla_seconds.set_total(seconds)
        return new_misses

    def total_misses(self) -> float:
        return self._misses.total()


# ---------------------------------------------------------------------------
# stall accounting
# ---------------------------------------------------------------------------


def timed_block_until_ready(
    x: Any, registry: MetricsRegistry, where: str = "unspecified"
) -> Any:
    """``jax.block_until_ready`` that accounts its stall time.

    Algorithm code that must host-sync on the serving path should do it
    through here (and suppress the host-sync lint with a reason): the
    stall lands in ``pio_device_stall_seconds_total{where=...}`` and the
    ``pio_device_fetch_seconds`` histogram instead of disappearing into
    the request wall time. On the *training* path the same call is what
    the ``train-unaccounted-sync`` lint demands: when a train profile is
    recording (``obs.xray``), the stall is additionally attributed to the
    profile's current phase so device time can't leak out of the step
    timeline.
    """
    import jax

    t0 = time.perf_counter()
    out = jax.block_until_ready(x)
    elapsed = time.perf_counter() - t0
    from predictionio_tpu.obs import xray

    prof = xray.current_profile()
    if prof is not None:
        prof.note_device_time(elapsed, where)
    registry.counter(
        "pio_device_stall_seconds_total",
        "cumulative seconds spent blocked on device->host synchronization",
        labelnames=("where",),
    ).inc(elapsed, where=where)
    registry.histogram(
        "pio_device_fetch_seconds",
        "device->host fetch / block_until_ready stall durations",
    ).observe(elapsed)
    return out


__all__ = [
    "CompileWatcher",
    "install_jax_monitoring",
    "monitoring_totals",
    "timed_block_until_ready",
]
