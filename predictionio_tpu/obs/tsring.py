"""Durable telemetry ring: a fixed-size on-disk history of fleet snapshots.

``/metrics`` federation is scrape-instant — the moment a worker dies or a
gateway restarts, the history an incident needs (queue depth climbing,
burn rate crossing, a replica flapping) is gone. This module keeps a
bounded, crash-safe window of it on disk:

- **Fixed size.** The ring is ``segments`` JSONL files of at most
  ``segment_records`` records each; when the active segment fills, the
  writer rotates to the next slot and truncates whatever the oldest
  cycle left there. Total disk use is bounded by construction — the ring
  can run for months without an operator thinking about it.
- **Atomic segment writes.** Each record is one ``json.dumps`` line
  written with a single ``write()`` + flush; a reader never sees half a
  record *as a record* because anything that does not parse as a
  complete JSON line (the torn tail of a crashed writer) is skipped on
  read. Rotation truncates via ``O_TRUNC`` open — a crash mid-rotation
  leaves either the old segment (stale seqs, superseded on read) or an
  empty file, both of which resume cleanly.
- **Crash-safe resume.** Every record carries a monotonically increasing
  ``seq``. On open, the ring scans all segments, finds the highest seq
  and its segment, and continues appending there — a restarted gateway
  picks up exactly where the dead one stopped, and ``window()`` serves
  the pre-crash history (the acceptance property ``pio top --history``
  leans on).

Queries: :meth:`TelemetryRing.window` (records newer than ``now - s``,
what ``GET /telemetry/window?s=N`` serves) and :meth:`TelemetryRing.tail`
(last N records, what incident bundles embed). Stdlib-only — `pio top`
and the CLI read rings without dragging in jax/aiohttp.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

_SEGMENT_PREFIX = "seg-"
_SEGMENT_SUFFIX = ".jsonl"


class TelemetryRing:
    """Bounded on-disk ring of JSON snapshot records.

    One writer (the gateway/supervisor process), any number of readers
    (the CLI reads the directory directly). Writer methods are
    thread-safe within the process; cross-process single-writer
    discipline is the caller's (the fleet parent owns its ring).
    """

    def __init__(
        self,
        dir_path: str,
        segment_records: int = 256,
        segments: int = 8,
        writer_id: str = "",
    ):
        if segments < 2:
            raise ValueError("ring needs at least 2 segments to rotate")
        if segment_records < 1:
            raise ValueError("segment_records must be >= 1")
        if writer_id and not writer_id.replace("-", "").isalnum():
            raise ValueError("writer_id must be alphanumeric/dashes")
        self.dir = dir_path
        self.segment_records = int(segment_records)
        self.segments = int(segments)
        # writer namespace (--gateways N): each writer owns its own
        # segment files (``seg-<writer>-NNNNN.jsonl``) so two gateways
        # sharing one obs dir never interleave — or truncate — one
        # segment. The default "" keeps the classic single-writer names.
        # READS merge every writer's segments (ordered by time), so the
        # autoscaler and `pio top --history` see the whole tier.
        self.writer_id = writer_id
        self._lock = threading.Lock()
        os.makedirs(self.dir, exist_ok=True)
        self._fh = None  # lazily (re)opened append handle
        self._resume()

    # ------------------------------------------------------------------ io
    def _segment_path(self, index: int) -> str:
        mid = f"{self.writer_id}-" if self.writer_id else ""
        return os.path.join(
            self.dir, f"{_SEGMENT_PREFIX}{mid}{index:05d}{_SEGMENT_SUFFIX}"
        )

    @staticmethod
    def _read_segment(path: str) -> list[dict[str, Any]]:
        """Parse one segment, skipping torn/corrupt lines (the tail a
        crashed writer may leave is data loss of ONE record, never a
        poisoned ring)."""
        records: list[dict[str, Any]] = []
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict) and "seq" in rec:
                        records.append(rec)
        except OSError:
            return []
        return records

    def _resume(self) -> None:
        """Find the live write position: the segment holding the highest
        seq, and how many records it already carries."""
        best_seq = -1
        active = 0
        active_count = 0
        for i in range(self.segments):
            recs = self._read_segment(self._segment_path(i))
            if not recs:
                continue
            top = max(int(r["seq"]) for r in recs)
            if top > best_seq:
                best_seq = top
                active = i
                active_count = len(recs)
        self._next_seq = best_seq + 1
        if active_count >= self.segment_records:
            # the active segment is already full: rotate immediately so
            # the first post-resume append does not overfill it
            self._active = (active + 1) % self.segments
            self._active_count = -1  # sentinel: truncate on next append
        else:
            self._active = active
            self._active_count = active_count

    def _open_active(self, truncate: bool) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        mode = "w" if truncate else "a"
        self._fh = open(
            self._segment_path(self._active), mode, encoding="utf-8"
        )
        if truncate:
            self._active_count = 0

    # -------------------------------------------------------------- writing
    def append(self, record: dict[str, Any]) -> int:
        """Append one snapshot; returns its seq. ``t`` (unix seconds) is
        stamped when absent — readers window on it."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            rec = dict(record)
            rec["seq"] = seq
            rec.setdefault("t", time.time())
            if self.writer_id:
                rec.setdefault("writer", self.writer_id)
            if self._fh is None:
                self._open_active(truncate=self._active_count < 0)
            elif self._active_count >= self.segment_records:
                self._active = (self._active + 1) % self.segments
                self._open_active(truncate=True)
            elif self._active_count < 0:
                self._open_active(truncate=True)
            line = json.dumps(rec, sort_keys=True)
            self._fh.write(line + "\n")
            self._fh.flush()
            self._active_count += 1
            return seq

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    @property
    def approx_count(self) -> int:
        """Cheap live-record estimate (no disk walk): seq count clamped
        to ring capacity — what the ``pio_telemetry_ring_records`` gauge
        reports per scrape."""
        capacity = self.segments * self.segment_records
        return min(self._next_seq, capacity)

    # -------------------------------------------------------------- reading
    def _all_segment_paths(self) -> dict[str, list[str]]:
        """Every writer's segment files in the directory, keyed by
        writer id ('' = the default single-writer namespace)."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return {}
        out: dict[str, list[str]] = {}
        for n in sorted(names):
            if not (
                n.startswith(_SEGMENT_PREFIX) and n.endswith(_SEGMENT_SUFFIX)
            ):
                continue
            stem = n[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
            writer, _sep, idx = stem.rpartition("-")
            if not idx.isdigit():
                continue
            out.setdefault(writer, []).append(os.path.join(self.dir, n))
        return out

    def records(self) -> list[dict[str, Any]]:
        """Every live record, oldest first. A single-writer directory
        reads in seq order exactly as before; a multi-writer one (the
        --gateways tier sharing an obs dir) merges every writer's
        segments ordered by record time (seqs are per-writer and tie
        within a writer)."""
        by_writer = self._all_segment_paths()
        recs_per: list[list[dict[str, Any]]] = []
        for paths in by_writer.values():
            recs: list[dict[str, Any]] = []
            for path in paths:
                recs.extend(self._read_segment(path))
            recs.sort(key=lambda r: int(r["seq"]))
            recs_per.append(recs)
        if not recs_per:
            return []
        if len(recs_per) == 1:
            return recs_per[0]
        out = [r for recs in recs_per for r in recs]
        out.sort(key=lambda r: (float(r.get("t", 0.0)), int(r["seq"])))
        return out

    def window(
        self, seconds: float, now: float | None = None
    ) -> list[dict[str, Any]]:
        """Records whose ``t`` falls inside the trailing window, oldest
        first — the ``GET /telemetry/window?s=N`` body."""
        now = time.time() if now is None else now
        cutoff = now - max(0.0, float(seconds))
        return [r for r in self.records() if float(r.get("t", 0.0)) >= cutoff]

    def tail(self, n: int) -> list[dict[str, Any]]:
        """Last ``n`` records, oldest first — what incident bundles embed."""
        recs = self.records()
        return recs[-max(0, int(n)):] if n else []

    def __len__(self) -> int:
        return len(self.records())


__all__ = ["TelemetryRing"]
