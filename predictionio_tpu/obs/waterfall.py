"""Per-request latency attribution: the phase waterfall.

BENCH_r01 measured the serve kernel at 0.025 ms and the e2e request at
67 ms — a ~200x gap with no instrument saying *where* the time goes.
This module is that instrument: every query is accounted into an explicit
sequence of phases that tile the request's wall clock, so "the server is
slow" decomposes into "the fetch phase is slow" with a concrete trace id
attached (TensorFlow-Serving made the dispatch/compute/fetch split a
first-class measurement before optimizing it; ALX credits exactly this
for finding its bottlenecks were host-side).

Phases, in request order (see docs/observability.md for the precise
boundaries):

- ``ingress_parse``   auth check, payload read, JSON decode
- ``cache``           version-keyed result-cache lookup (every request
                      observes its lookup cost; a HIT ends the request
                      here — its waterfall is parse -> cache -> respond,
                      no queue/dispatch/device phases at all)
- ``queue_wait``      micro-batch admission queue (incl. in-flight
                      backpressure while earlier batches occupy the
                      dispatch pipeline)
- ``batch_assembly``  draining queued peers into this batch
- ``dispatch``        decode -> supplement -> host-to-device enqueue
- ``device_compute``  blocked on device results (``predict_batch`` /
                      finalizers; algorithm host-syncs should route
                      through ``obs.jaxprof.timed_block_until_ready`` so
                      their stall also lands in the stall counter)
- ``fetch``           result distribution residual: executor hop +
                      unpack outside compute and serve
- ``serve``           serving.serve + top-k post-processing + encode
- ``respond``         future resolution -> response serialization

Every observation lands in ONE fixed-bucket histogram
(``pio_phase_seconds{phase=…}``) with the request's trace id captured as
the bucket's exemplar — a p99 outlier in any phase links to a concrete
trace in ``/traces/recent`` instead of an anonymous count. Batch-scoped
phases (assembly/dispatch/device/fetch/serve) are observed once per
*query*, valued at the batch's duration: every rider of a batch really
does wait out the whole batch, so per-query phase sums reconcile with
per-query e2e latency (the contract tests assert within 10%).

Stdlib-only, like the rest of the metrics layer.
"""

from __future__ import annotations

from typing import Any

from predictionio_tpu.obs.metrics import Histogram, MetricsRegistry

# request-ordered phase vocabulary; label values of pio_phase_seconds
PHASE_INGRESS_PARSE = "ingress_parse"
PHASE_CACHE = "cache"
PHASE_QUEUE_WAIT = "queue_wait"
PHASE_BATCH_ASSEMBLY = "batch_assembly"
PHASE_DISPATCH = "dispatch"
PHASE_DEVICE_COMPUTE = "device_compute"
PHASE_FETCH = "fetch"
PHASE_SERVE = "serve"
PHASE_RESPOND = "respond"

PHASES: tuple[str, ...] = (
    PHASE_INGRESS_PARSE,
    PHASE_CACHE,
    PHASE_QUEUE_WAIT,
    PHASE_BATCH_ASSEMBLY,
    PHASE_DISPATCH,
    PHASE_DEVICE_COMPUTE,
    PHASE_FETCH,
    PHASE_SERVE,
    PHASE_RESPOND,
)

PHASE_METRIC = "pio_phase_seconds"


class PhaseWaterfall:
    """The per-request phase histogram + its JSON snapshot.

    ``observe`` is one histogram observation under a per-metric lock —
    hot-path cheap. Negative durations (clock skew across threads,
    residual clamping) are floored at zero so the waterfall never renders
    a phase that "gave time back".
    """

    def __init__(self, registry: MetricsRegistry):
        self.hist: Histogram = registry.histogram(
            PHASE_METRIC,
            "per-request latency by serving phase "
            "(ingress_parse|cache|queue_wait|batch_assembly|dispatch|"
            "device_compute|fetch|serve|respond); bucket exemplars carry "
            "the trace id of the most recent observation",
            labelnames=("phase",),
        )

    def observe(
        self, phase: str, seconds: float, exemplar: str | None = None
    ) -> None:
        self.hist.observe(max(0.0, seconds), exemplar=exemplar, phase=phase)

    def snapshot(self) -> dict[str, Any]:
        """Per-phase summaries + exemplars, request-ordered — the JSON the
        ``/slo`` report and dashboards embed."""
        out: dict[str, Any] = {}
        for phase in PHASES:
            s = self.hist.summary(phase=phase)
            if not s.get("count"):
                continue
            out[phase] = {
                **{k: round(float(v), 6) for k, v in s.items()},
                "exemplars": self.hist.exemplars(phase=phase),
            }
        return out


def phase_tags_ms(**phase_seconds: float) -> dict[str, float]:
    """Span-tag helper: ``{phase}_ms`` rounded, skipping Nones — keeps the
    query.batch/ingress span tags consistent with the histogram phases."""
    return {
        f"{name}_ms": round(max(0.0, s) * 1000.0, 3)
        for name, s in phase_seconds.items()
        if s is not None
    }


__all__ = [
    "PHASES",
    "PHASE_METRIC",
    "PHASE_INGRESS_PARSE",
    "PHASE_CACHE",
    "PHASE_QUEUE_WAIT",
    "PHASE_BATCH_ASSEMBLY",
    "PHASE_DISPATCH",
    "PHASE_DEVICE_COMPUTE",
    "PHASE_FETCH",
    "PHASE_SERVE",
    "PHASE_RESPOND",
    "PhaseWaterfall",
    "phase_tags_ms",
]
