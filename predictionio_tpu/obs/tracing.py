"""Request-scoped tracing: trace ids, spans, a ring buffer, JSON logs.

A trace id is minted (or accepted from the ``X-Pio-Trace-Id`` header) at
ingress and rides a :mod:`contextvars` variable through the asyncio
handlers; thread hops (the micro-batcher's dispatch/fetch workers, the
event server's storage executor) re-install it explicitly because
``run_in_executor`` does not copy the caller's context.

Every finished span is (a) appended to a bounded ring buffer served at
``/traces/recent`` and (b) emitted as one JSON line on the ``pio.trace``
logger — the structured log the acceptance trail greps for a single trace
id across ingress, batch, and storage spans. Span kinds used by the
framework: ``ingress`` (HTTP arrival), ``batch`` (micro-batch queue +
device dispatch/fetch, with wall/queue/device timings in tags),
``storage`` (DAO method via :mod:`predictionio_tpu.data.storage.traced`),
``serving`` (per-query decode/serve work).

Import-light by design (stdlib only): `pio top`, the lint CLI, and the
event server all reach this module without dragging in jax/numpy.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import logging
import threading
import time
import uuid
from collections import deque
from typing import Any, Iterator

TRACE_HEADER = "X-Pio-Trace-Id"

# one trace id per logical request, carried across awaits by contextvars
_current_trace: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_trace_id", default=None
)

_trace_logger = logging.getLogger("pio.trace")


def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace_id() -> str | None:
    return _current_trace.get()


def set_trace_id(trace_id: str | None) -> contextvars.Token:
    """Install ``trace_id`` for the current context; pair with
    :func:`reset_trace_id` (thread hops install/reset around each unit of
    work for one request)."""
    return _current_trace.set(trace_id)


def reset_trace_id(token: contextvars.Token) -> None:
    _current_trace.reset(token)


def get_trace_logger() -> logging.Logger:
    """The structured span logger (one JSON object per line). Serving-path
    code should log through spans/this logger, not ``print`` or the root
    logger — the ``obs-unstructured-log`` lint rule enforces it."""
    return _trace_logger


@dataclasses.dataclass
class Span:
    trace_id: str
    name: str
    kind: str = "internal"
    span_id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:8])
    # timing hygiene contract: start_time is a WALL-CLOCK display anchor
    # only (correlating spans with external logs); every duration in this
    # module — span durations, phase timings, queue waits — is measured
    # from time.perf_counter()/time.monotonic(), never as a wall-clock
    # delta, so an NTP step can shift where a span *appears* on a timeline
    # but can never corrupt how long anything *took*
    start_time: float = dataclasses.field(default_factory=time.time)
    duration_s: float = 0.0
    status: str = "ok"
    tags: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "name": self.name,
            "kind": self.kind,
            "startTime": round(self.start_time, 6),
            "durationMs": round(self.duration_s * 1000.0, 3),
            "status": self.status,
            "tags": self.tags,
        }


class Tracer:
    """Span sink: bounded ring buffer + JSON log emission.

    One process-wide default instance (:func:`get_tracer`) is shared by
    the servers and the storage wrappers, mirroring how all structured
    logs converge on one logging tree; tests may construct private
    tracers for isolation.
    """

    def __init__(self, ring_size: int = 512):
        self._ring: deque[Span] = deque(maxlen=max(1, ring_size))
        self._lock = threading.Lock()
        self.spans_recorded = 0

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        kind: str = "internal",
        trace_id: str | None = None,
        **tags: Any,
    ) -> Iterator[Span]:
        """Time a block as one span. The span is yielded so callers can
        attach tags mid-flight; an escaping exception marks the status
        with the exception type and re-raises."""
        sp = Span(
            trace_id=trace_id or current_trace_id() or mint_trace_id(),
            name=name,
            kind=kind,
            tags=dict(tags),
        )
        t0 = time.perf_counter()
        try:
            yield sp
        except BaseException as exc:
            sp.status = type(exc).__name__
            raise
        finally:
            sp.duration_s = time.perf_counter() - t0
            self.record(sp)

    def record_span(
        self,
        name: str,
        kind: str,
        duration_s: float,
        trace_id: str | None = None,
        status: str = "ok",
        **tags: Any,
    ) -> Span:
        """Record an already-timed span (the micro-batcher measures queue
        /dispatch/fetch itself and reports per-query afterwards)."""
        sp = Span(
            trace_id=trace_id or current_trace_id() or mint_trace_id(),
            name=name,
            kind=kind,
            start_time=time.time() - duration_s,
            duration_s=duration_s,
            status=status,
            tags=dict(tags),
        )
        self.record(sp)
        return sp

    def record(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            self.spans_recorded += 1
        if _trace_logger.isEnabledFor(logging.INFO):
            _trace_logger.info("%s", json.dumps(span.to_json_dict()))

    def recent(self, limit: int | None = None) -> list[dict[str, Any]]:
        """Newest-first JSON dicts for ``/traces/recent``."""
        with self._lock:
            spans = list(self._ring)
        spans.reverse()
        if limit is not None:
            spans = spans[: max(0, limit)]
        return [s.to_json_dict() for s in spans]

    def find(self, trace_id: str) -> list[dict[str, Any]]:
        """All ring-resident spans of one trace, oldest first."""
        with self._lock:
            return [
                s.to_json_dict() for s in self._ring if s.trace_id == trace_id
            ]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_default_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer shared by servers and storage wrappers."""
    return _default_tracer
