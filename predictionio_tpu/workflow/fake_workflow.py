"""FakeRun — run an arbitrary function under the exact workflow environment.

Reference parity: ``core/src/main/scala/org/apache/predictionio/workflow/
FakeWorkflow.scala:18-109`` — ``FakeRun`` is an ``Evaluation`` whose
"evaluator" just calls a user function with the SparkContext, and whose
result carries ``noSave = true`` so nothing is persisted. It exists so new
features can be developed with ``pio eval HelloWorld`` and the full env
(storage config, logging, cleanup hooks) without defining DASE components.

Here the function receives the :class:`WorkflowContext` (the SparkContext
analogue: mesh + storage + mode).
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable

from predictionio_tpu.workflow.context import WorkflowContext


@dataclasses.dataclass
class FakeEvalResult:
    """Sentinel result; ``no_save=True`` keeps run_evaluation from writing an
    EvaluationInstance (ref ``FakeEvalResult.noSave``)."""

    value: Any = None
    no_save: bool = True

    def one_liner(self) -> str:
        return "FakeRun (not persisted)"

    def to_json_dict(self) -> dict[str, Any]:
        return {"fakeRun": True}

    def to_html(self) -> str:
        return "<p>FakeRun (not persisted)</p>"


class FakeRun:
    """Wraps ``func(ctx) -> Any`` as an Evaluation-shaped object accepted by
    ``run_evaluation`` and ``pio eval`` (ref ``FakeRun`` trait usage:
    ``pio eval HelloWorld`` with ``func = f``).

    Subclass and set ``func`` (plain function, ``@staticmethod``, or a
    lambda — all three spellings work), or construct with the function::

        class HelloWorld(FakeRun):
            @staticmethod
            def func(ctx):
                print("hello from", ctx.mode)
    """

    func: Callable[[WorkflowContext], Any] | None = None

    def __init__(self, func: Callable[[WorkflowContext], Any] | None = None):
        if func is not None:
            self.func = func  # type: ignore[assignment]

    def run(self, ctx: WorkflowContext) -> FakeEvalResult:
        # instance attribute first (set by __init__ — a plain function there
        # never binds); then the CLASS DICT, bypassing descriptor binding: a
        # plain ONE-ARGUMENT function assigned as `func = my_fn` (the
        # natural spelling, @staticmethod omitted) would otherwise arrive
        # as a bound method and receive the FakeRun instance in place of
        # the context. A conventional method spelling (def func(self, ctx))
        # still binds: arity decides.
        fn = self.__dict__.get("func")
        if fn is None:
            for klass in type(self).__mro__:
                if "func" in klass.__dict__:
                    raw = klass.__dict__["func"]
                    if isinstance(raw, (staticmethod, classmethod)):
                        fn = raw.__get__(None, type(self))
                    elif callable(raw):
                        try:
                            n_pos = sum(
                                1
                                for p in inspect.signature(
                                    raw
                                ).parameters.values()
                                if p.kind
                                in (
                                    p.POSITIONAL_ONLY,
                                    p.POSITIONAL_OR_KEYWORD,
                                )
                            )
                        except (TypeError, ValueError):
                            n_pos = 1
                        # a callable INSTANCE (defines __call__, no
                        # __get__) is not a descriptor — invoke it
                        # directly regardless of arity
                        fn = (
                            raw.__get__(self, type(self))
                            if n_pos >= 2 and hasattr(raw, "__get__")
                            else raw
                        )
                    else:
                        fn = raw
                    break
        if fn is None:
            raise ValueError("FakeRun has no func")
        return FakeEvalResult(value=fn(ctx))
