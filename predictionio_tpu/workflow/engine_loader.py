"""Engine discovery: engine.json variants + factory loading.

Reference parity: ``WorkflowUtils.getEngine`` (reflective factory loading,
``core/.../workflow/WorkflowUtils.scala``), the engine.json variant format
(``tests/pio_tests/engines/recommendation-engine/engine.json``), and
``template.json`` min-version checking (``tools/.../commands/Template.scala:35-69``).

An engine directory contains::

    engine.json     {"id", "description", "engineFactory": "pkg.module.fn",
                     "datasource": ..., "algorithms": [...], "serving": ...}
    template.json   {"pio": {"version": {"min": "x.y.z"}}}   (optional)
    <python files>  importable because the engine dir is added to sys.path

``engineFactory`` is a dotted path to a callable returning an Engine, or to
an EngineFactory class. The reference compiled jars with sbt; here there is
no build step — the CLI's `build` verb only validates.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import os
import sys
from typing import Any, Mapping

import predictionio_tpu
from predictionio_tpu.controller.engine import Engine, EngineFactory


class EngineLoadError(RuntimeError):
    pass


@dataclasses.dataclass
class EngineManifest:
    engine_id: str
    version: str
    variant: str  # variant id from engine.json ("id" key, default "default")
    engine_factory: str
    description: str = ""
    variant_json: dict[str, Any] = dataclasses.field(default_factory=dict)
    engine_dir: str = "."


def load_engine_factory(dotted: str) -> Engine:
    """Resolve "pkg.module.attr" to an Engine instance."""
    module_name, _, attr = dotted.rpartition(".")
    if not module_name:
        raise EngineLoadError(f"engineFactory {dotted!r} must be a dotted path")
    try:
        module = importlib.import_module(module_name)
    except ImportError as exc:
        raise EngineLoadError(f"cannot import {module_name}: {exc}") from exc
    try:
        factory = getattr(module, attr)
    except AttributeError as exc:
        raise EngineLoadError(f"{module_name} has no attribute {attr}") from exc
    if isinstance(factory, Engine):
        return factory
    if isinstance(factory, type) and issubclass(factory, EngineFactory):
        return factory()()
    if callable(factory):
        engine = factory()
        if not isinstance(engine, Engine):
            raise EngineLoadError(
                f"{dotted} returned {type(engine).__name__}, not an Engine"
            )
        return engine
    raise EngineLoadError(f"{dotted} is not an Engine factory")


def _check_template_version(engine_dir: str) -> None:
    path = os.path.join(engine_dir, "template.json")
    if not os.path.exists(path):
        return
    with open(path) as f:
        data = json.load(f)
    min_version = ((data.get("pio") or {}).get("version") or {}).get("min")
    if not min_version:
        return

    def vtuple(v: str) -> tuple[int, ...]:
        return tuple(int(x) for x in v.split(".") if x.isdigit())

    if vtuple(predictionio_tpu.__version__) < vtuple(min_version):
        raise EngineLoadError(
            f"template requires framework >= {min_version}, "
            f"this is {predictionio_tpu.__version__}"
        )


def load_manifest(
    engine_dir: str, variant_path: str | None = None
) -> EngineManifest:
    """Read engine.json (or an alternate variant file) from an engine dir."""
    engine_dir = os.path.abspath(engine_dir)
    variant_path = variant_path or os.path.join(engine_dir, "engine.json")
    if not os.path.isabs(variant_path):
        variant_path = os.path.join(engine_dir, variant_path)
    if not os.path.exists(variant_path):
        raise EngineLoadError(f"engine variant file not found: {variant_path}")
    _check_template_version(engine_dir)
    with open(variant_path) as f:
        variant = json.load(f)
    factory = variant.get("engineFactory")
    if not factory:
        raise EngineLoadError(f"{variant_path} missing engineFactory")
    # Engine identity: the variant "id" when it is distinctive, else the
    # absolute engine directory — matching the reference, which registers a
    # manifest per engine directory at `pio build`. A generic/absent id must
    # not collide across engines or `deploy` would resolve another engine's
    # COMPLETED instances and serve the wrong model.
    variant_id = variant.get("id")
    engine_id = (
        variant_id if variant_id and variant_id != "default" else engine_dir
    )
    return EngineManifest(
        engine_id=engine_id,
        version=variant.get("version", "1"),
        variant=os.path.basename(variant_path),
        engine_factory=factory,
        description=variant.get("description", ""),
        variant_json=variant,
        engine_dir=engine_dir,
    )


def load_engine(
    engine_dir: str, variant_path: str | None = None
) -> tuple[EngineManifest, Engine]:
    manifest = load_manifest(engine_dir, variant_path)
    if manifest.engine_dir not in sys.path:
        sys.path.insert(0, manifest.engine_dir)
    engine = load_engine_factory(manifest.engine_factory)
    return manifest, engine
