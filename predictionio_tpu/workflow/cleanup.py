"""Global cleanup hooks run in the ``finally`` of every workflow main
(ref ``core/.../workflow/CleanupFunctions.scala:1-65``)."""

from __future__ import annotations

import logging
from typing import Callable

logger = logging.getLogger(__name__)


class CleanupFunctions:
    _fns: list[Callable[[], None]] = []

    @classmethod
    def add(cls, fn: Callable[[], None]) -> None:
        cls._fns.append(fn)

    @classmethod
    def run(cls) -> None:
        for fn in cls._fns:
            try:
                fn()
            except Exception:
                logger.exception("cleanup function failed")

    @classmethod
    def clear(cls) -> None:
        cls._fns.clear()
