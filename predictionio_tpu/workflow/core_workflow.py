"""CoreWorkflow — train/eval runs with metadata + model persistence.

Reference parity: ``core/.../workflow/CoreWorkflow.scala`` — ``runTrain``
(:45-102): insert EngineInstance, engine.train, serialize models into the
Models repo, mark COMPLETED; ``runEvaluation`` (:104-164): insert
EvaluationInstance, run evaluator, persist one-liner/HTML/JSON results.
Train wall-clock is recorded explicitly (the reference only kept
startTime/endTime implicitly — SURVEY.md section 6 calls this out as a gap).
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import json
import logging
import os
import sys
import time
from typing import Any

from predictionio_tpu.controller.engine import Engine, EngineParams, TrainOptions
from predictionio_tpu.data.storage.base import (
    EngineInstance,
    EngineInstanceStatus,
    EvaluationInstance,
    EvaluationInstanceStatus,
    Model,
)
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import xray
from predictionio_tpu.obs.profiler import maybe_profile_train
from predictionio_tpu.workflow import model_io
from predictionio_tpu.workflow.cleanup import CleanupFunctions
from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.engine_loader import EngineManifest

logger = logging.getLogger(__name__)
UTC = _dt.timezone.utc


def run_train(
    engine: Engine,
    manifest: EngineManifest,
    engine_params: EngineParams,
    ctx: WorkflowContext | None = None,
    options: TrainOptions | None = None,
    storage: Storage | None = None,
    batch: str = "",
    env: dict[str, str] | None = None,
    registry_dir: str | None = None,
    keep_versions: int = 5,
) -> str:
    """Run training end-to-end; returns the engine-instance id.

    With a registry configured (``registry_dir`` argument or the
    ``PIO_REGISTRY_DIR`` env var) the serialized blob is ALSO published as
    a content-addressed, sha256-checksummed artifact with a lineage
    manifest — the unit ``pio models`` and the progressive-rollout router
    operate on. Publish failures never fail the train: the metadata/model
    stores above are written first and remain authoritative for recovery.

    Multi-host: every process runs the same compute (SPMD — non-coordinator
    hosts must participate in the collectives inside ``engine.train``), but
    only process 0 touches the metadata/model stores; the others return ""
    (ref: the Spark driver was the single metadata writer,
    CoreWorkflow.scala:45-102).
    """
    storage = storage or Storage.instance()
    ctx = ctx or WorkflowContext(mode="training", _storage=storage, batch=batch)
    # multi-host detection via the launcher's env contract, NOT an
    # unconditional jax.process_count(): calling into jax here would
    # initialize the XLA backend for every train — including pure-host
    # LocalAlgorithm engines that never touch jax — contending for the
    # accelerator with any already-deployed server on the same machine.
    # A deployment that initializes jax.distributed programmatically
    # (without the launcher env contract) is still covered: when jax is
    # ALREADY imported AND its distributed runtime is initialized,
    # consulting it is safe — ``is_initialized`` only reads client state,
    # and ``process_count`` can no longer trigger a *fresh* backend init
    # fight because distributed init implies the deployment owns the
    # device. Without the check every such process would take the
    # coordinator path and concurrently write metadata/models.
    multi_host = bool(
        os.environ.get("PIO_COORDINATOR")
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    if not multi_host and "jax" in sys.modules:
        import jax

        if getattr(jax.distributed, "is_initialized", lambda: False)():
            multi_host = jax.process_count() > 1
    if multi_host:
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            try:
                # non-coordinator workers profile too (PIO_PROFILE_DIR gate):
                # their bundle context names the process index so a per-host
                # straggler is attributable
                with maybe_profile_train(
                    context={
                        "engine": manifest.engine_id,
                        "engineVersion": manifest.version,
                        "processIndex": jax.process_index(),
                    }
                ):
                    models = engine.train(ctx, engine_params, options)
                if not (
                    options
                    and (options.stop_after_read or options.stop_after_prepare)
                ):
                    # serialization includes the cross-host gather of sharded
                    # model arrays (model_to_host), which is itself a
                    # collective — every process must run it even though only
                    # process 0 persists
                    engine.make_serializable_models(ctx, engine_params, models)
            finally:
                # same contract as the coordinator path's finally: cleanup
                # hooks run even when a worker's collective aborts
                CleanupFunctions.run()
            logger.info(
                "process %d finished (coordinator persists)", jax.process_index()
            )
            return ""
    instances = storage.get_meta_data_engine_instances()
    params_json = Engine.engine_params_to_json(engine_params)
    instance = EngineInstance(
        id="",
        status=EngineInstanceStatus.INIT,
        start_time=_dt.datetime.now(tz=UTC),
        end_time=_dt.datetime.now(tz=UTC),
        engine_id=manifest.engine_id,
        engine_version=manifest.version,
        engine_variant=manifest.variant,
        engine_factory=manifest.engine_factory,
        batch=batch,
        env=env or {},
        **params_json,
    )
    instance_id = instances.insert(instance)
    logger.info("engine instance %s created", instance_id)
    # the step profiler (obs/xray): phases tile the train wall clock,
    # every iteration becomes a train.step span, and the finished profile
    # rides the registry manifest as this version's training evidence.
    # PIO_XRAY=0 opts out (restores the fully-async unprofiled dispatch).
    profile: xray.TrainProfile | None = None
    if os.environ.get("PIO_XRAY", "1").lower() not in ("0", "false", "off"):
        profile = xray.TrainProfile(trainer=f"{manifest.engine_id}:batch")
    t0 = time.perf_counter()
    try:
        instance.status = EngineInstanceStatus.TRAINING
        instances.update(instance)
        with contextlib.ExitStack() as scope:
            if profile is not None:
                scope.enter_context(xray.use_profile(profile))
                scope.enter_context(profile.measure())
            # device-trace gate (PIO_PROFILE_DIR): the trace now lands as a
            # content-addressed profile bundle whose manifest cross-links
            # the xray TrainProfile running in this same scope
            with maybe_profile_train(
                context={
                    "engine": manifest.engine_id,
                    "engineVersion": manifest.version,
                    "batch": batch,
                    "instanceId": instance_id,
                },
                parts_fn=lambda: (
                    {"xray": profile.to_json_dict()}
                    if profile is not None
                    else {}
                ),
            ):
                models = engine.train(ctx, engine_params, options)
            if options and (
                options.stop_after_read or options.stop_after_prepare
            ):
                instance.status = EngineInstanceStatus.COMPLETED
                instance.end_time = _dt.datetime.now(tz=UTC)
                instances.update(instance)
                return instance_id
            with xray.phase(xray.PHASE_HOST_ETL):
                persistable = engine.make_serializable_models(
                    ctx, engine_params, models
                )
                blob = model_io.serialize_models(persistable)
        if profile is not None:
            profile.finish()
        storage.get_model_data_models().insert(Model(instance_id, blob))
        wall = time.perf_counter() - t0
        instance.status = EngineInstanceStatus.COMPLETED
        instance.end_time = _dt.datetime.now(tz=UTC)
        instance.spark_conf = {"train_wall_clock_sec": f"{wall:.3f}"}
        instances.update(instance)
        _publish_to_registry(
            manifest,
            instance_id,
            blob,
            params_json,
            wall,
            batch,
            registry_dir,
            keep_versions,
            train_profile=profile.to_json_dict() if profile is not None else {},
            models=persistable,
        )
        logger.info(
            "training completed: instance %s, %.2fs, %d model(s), %d byte blob",
            instance_id,
            wall,
            len(models),
            len(blob),
        )
        return instance_id
    except Exception:
        instance.status = EngineInstanceStatus.FAILED
        instance.end_time = _dt.datetime.now(tz=UTC)
        instances.update(instance)
        raise
    finally:
        CleanupFunctions.run()


def _publish_to_registry(
    manifest: EngineManifest,
    instance_id: str,
    blob: bytes,
    params_json: dict[str, str],
    wall_s: float,
    batch: str,
    registry_dir: str | None,
    keep_versions: int,
    train_profile: dict | None = None,
    models: list[Any] | None = None,
) -> None:
    """Write the trained blob into the artifact registry with its lineage
    manifest — including the train profile, so every version carries its
    training evidence (`pio models show` answers "how was this trained,
    how long, how big"). Atomic (tmp+rename inside the store);
    best-effort by contract — a broken registry disk must not fail a
    completed train.

    When the trained models expose an item-vector table and the corpus
    clears the ANN threshold (predictionio_tpu/ann, docs/ann.md), the
    version also gets its retrieval index built and pinned here — the
    end-of-train half of the index lifecycle."""
    registry_dir = registry_dir or os.environ.get("PIO_REGISTRY_DIR")
    if not registry_dir:
        return
    try:
        from predictionio_tpu.registry import (
            ArtifactStore,
            ModelManifest,
            params_hash_of,
        )

        store = ArtifactStore(registry_dir)
        published = store.publish(
            ModelManifest(
                version="",
                engine_id=manifest.engine_id,
                engine_version=manifest.version,
                engine_variant=manifest.variant,
                engine_factory=manifest.engine_factory,
                instance_id=instance_id,
                params_hash=params_hash_of(params_json),
                data_span={
                    "trainedAt": ModelManifest.now_iso(),
                    "batch": batch,
                    "trainWallClockSec": round(wall_s, 3),
                },
                train_profile=train_profile or {},
            ),
            blob,
            keep_last=keep_versions,
        )
        logger.info(
            "registry: published %s (instance %s)", published.version, instance_id
        )
        if models:
            from predictionio_tpu.ann import lifecycle as ann_lifecycle

            ann_lifecycle.build_for_version(
                store, manifest.engine_id, published.version, models
            )
    except Exception:
        logger.exception(
            "registry publish failed (metadata store remains authoritative)"
        )


def load_models_for_instance(
    engine: Engine,
    engine_params: EngineParams,
    instance_id: str,
    ctx: WorkflowContext | None = None,
    storage: Storage | None = None,
) -> list[Any]:
    """Model-repo blob -> deployable models (ref CreateServer.scala:196-220)."""
    storage = storage or Storage.instance()
    ctx = ctx or WorkflowContext(mode="serving", _storage=storage)
    record = storage.get_model_data_models().get(instance_id)
    if record is None:
        raise RuntimeError(f"no model blob for engine instance {instance_id}")
    persisted = model_io.deserialize_models(record.models)
    return engine.prepare_deploy(ctx, engine_params, persisted)


def run_grid_evaluation(
    evaluation_source: "Any",
    ctx: WorkflowContext | None = None,
    storage: Storage | None = None,
    batch: str = "",
    **grid_kwargs: Any,
) -> tuple[str, Any]:
    """Run an Evaluation through the parallel, resumable evaluation grid
    (predictionio_tpu/tuning, docs/evaluation.md) with the same
    EvaluationInstance bookkeeping as :func:`run_evaluation`: the
    metadata store keeps its one-liner/JSON/HTML results row, the grid
    keeps its durable cell ledger, and (when publishing) the winner
    rides the registry as a candidate. Returns (instance_id, GridReport).
    """
    from predictionio_tpu.tuning import run_grid
    from predictionio_tpu.tuning.cells import resolve_evaluation

    storage = storage or Storage.instance()
    ctx = ctx or WorkflowContext(mode="evaluation", _storage=storage, batch=batch)
    evaluation = grid_kwargs.pop("evaluation", None) or resolve_evaluation(
        evaluation_source
    )
    instances = storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        id="",
        status=EvaluationInstanceStatus.INIT,
        start_time=_dt.datetime.now(tz=UTC),
        end_time=_dt.datetime.now(tz=UTC),
        evaluation_class=type(evaluation).__module__
        + "."
        + type(evaluation).__qualname__,
        batch=batch,
    )
    instance_id = ""

    def record_start() -> None:
        # inserted only AFTER run_grid's argument/ledger validation: a
        # flag typo (ledger-exists-without-resume, missing registry for
        # --publish, ...) must not leave a forever-EVALUATING zombie row
        # in the metadata store on every retry
        nonlocal instance_id
        instance_id = instances.insert(instance)
        instance.status = EvaluationInstanceStatus.EVALUATING
        instances.update(instance)

    try:
        # workers>0 rebuild the evaluation by name in each process — hand
        # the original source through; the resolved instance serves the
        # in-process path
        source = (
            evaluation_source
            if isinstance(evaluation_source, str)
            or not hasattr(evaluation_source, "run")
            else evaluation
        )
        report = run_grid(
            source,
            ctx=ctx,
            storage=storage,
            evaluation=evaluation,
            on_validated=record_start,
            **grid_kwargs,
        )
    except BaseException:
        # stays EVALUATING — never EVALCOMPLETED; the ledger holds the
        # finished cells for a --resume
        if instance_id:
            instance.end_time = _dt.datetime.now(tz=UTC)
            instances.update(instance)
        CleanupFunctions.run()
        raise
    result = report.evaluator_result
    instance.status = EvaluationInstanceStatus.EVALCOMPLETED
    instance.end_time = _dt.datetime.now(tz=UTC)
    instance.evaluator_results = report.one_liner()
    if result is not None:
        instance.evaluator_results_json = json.dumps(result.to_json_dict())
        instance.evaluator_results_html = result.to_html()
    instances.update(instance)
    CleanupFunctions.run()
    return instance_id, report


def run_evaluation(
    evaluation: "Any",
    ctx: WorkflowContext | None = None,
    storage: Storage | None = None,
    batch: str = "",
) -> tuple[str, Any]:
    """Run an Evaluation (engine + metric + params list); persists an
    EvaluationInstance with one-liner/JSON/HTML results. Returns
    (instance_id, evaluator result)."""
    storage = storage or Storage.instance()
    ctx = ctx or WorkflowContext(mode="evaluation", _storage=storage, batch=batch)
    instances = storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        id="",
        status=EvaluationInstanceStatus.INIT,
        start_time=_dt.datetime.now(tz=UTC),
        end_time=_dt.datetime.now(tz=UTC),
        evaluation_class=type(evaluation).__module__
        + "."
        + type(evaluation).__qualname__,
        batch=batch,
    )
    instance_id = instances.insert(instance)
    instance.status = EvaluationInstanceStatus.EVALUATING
    instances.update(instance)
    result = evaluation.run(ctx)
    if getattr(result, "no_save", False):
        # ref CoreWorkflow.scala:140-142 — FakeRun results are not persisted
        logger.info("evaluation result not inserted into database (no_save)")
    else:
        instance.status = EvaluationInstanceStatus.EVALCOMPLETED
        instance.end_time = _dt.datetime.now(tz=UTC)
        instance.evaluator_results = result.one_liner()
        instance.evaluator_results_json = json.dumps(result.to_json_dict())
        instance.evaluator_results_html = result.to_html()
        instances.update(instance)
    CleanupFunctions.run()
    return instance_id, result
