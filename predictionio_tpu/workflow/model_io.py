"""Model serialization for the model repository.

Reference parity: Kryo blobs via ``KryoInstantiator``
(``CreateServer.scala:59-73``, ``CoreWorkflow.scala:76-81``). Here models are
pickled pytrees; every jax array has already been pulled to host numpy by
``make_persistent_model`` so checkpoints are device- and sharding-agnostic
(train on a pod slice, deploy on one host).

Format v02 (``PIOTPU02``)::

    magic(8) ‖ zlib(pickle(models)) ‖ sha256(compressed)(32) ‖ len(compressed)(8, big-endian)

The footer makes corruption a *diagnosis*, not a mystery: a truncated or
bit-flipped blob used to surface as an opaque ``zlib.error`` or a pickle
exception deep in deploy; now it raises :class:`ModelIntegrityError`
naming what mismatched. v01 blobs (no footer) are still read — integrity
failures there are detected at decompress/unpickle time and wrapped in
the same error type.
"""

from __future__ import annotations

import hashlib
import pickle
import struct
import zlib
from typing import Any

MAGIC = b"PIOTPU02"
MAGIC_V1 = b"PIOTPU01"

_FOOTER = struct.Struct(">32sQ")  # sha256(compressed) ‖ compressed length


class ModelIntegrityError(ValueError):
    """The blob is not an intact predictionio_tpu model artifact (bad
    magic, truncated, or checksum mismatch)."""


def serialize_models(models: list[Any]) -> bytes:
    payload = pickle.dumps(models, protocol=pickle.HIGHEST_PROTOCOL)
    compressed = zlib.compress(payload, level=1)
    footer = _FOOTER.pack(hashlib.sha256(compressed).digest(), len(compressed))
    return MAGIC + compressed + footer


def deserialize_models(blob: bytes) -> list[Any]:
    if blob.startswith(MAGIC):
        body = blob[len(MAGIC):]
        if len(body) < _FOOTER.size:
            raise ModelIntegrityError(
                f"model blob truncated: {len(body)} bytes cannot hold the "
                f"{_FOOTER.size}-byte integrity footer"
            )
        compressed, footer = body[: -_FOOTER.size], body[-_FOOTER.size:]
        digest, length = _FOOTER.unpack(footer)
        if len(compressed) != length:
            raise ModelIntegrityError(
                f"model blob truncated: footer says {length} payload bytes, "
                f"found {len(compressed)}"
            )
        actual = hashlib.sha256(compressed).digest()
        if actual != digest:
            raise ModelIntegrityError(
                f"model blob corrupt: payload sha256 {actual.hex()[:12]}… "
                f"does not match footer {digest.hex()[:12]}…"
            )
    elif blob.startswith(MAGIC_V1):
        compressed = blob[len(MAGIC_V1):]  # v01: no footer to verify
    else:
        raise ModelIntegrityError(
            "not a predictionio_tpu model blob (bad magic)"
        )
    try:
        payload = zlib.decompress(compressed)
        return pickle.loads(payload)
    except (zlib.error, pickle.UnpicklingError, EOFError) as exc:
        # only reachable for v01 blobs (v02 verified the checksum above) or
        # a pickle stream damaged before v02 framing existed
        raise ModelIntegrityError(
            f"model blob corrupt (legacy v01 format, no checksum): {exc}"
        ) from exc
