"""Model serialization for the model repository.

Reference parity: Kryo blobs via ``KryoInstantiator``
(``CreateServer.scala:59-73``, ``CoreWorkflow.scala:76-81``). Here models are
pickled pytrees; every jax array has already been pulled to host numpy by
``make_persistent_model`` so checkpoints are device- and sharding-agnostic
(train on a pod slice, deploy on one host). A small header versions the
format.
"""

from __future__ import annotations

import io
import pickle
import zlib
from typing import Any

MAGIC = b"PIOTPU01"


def serialize_models(models: list[Any]) -> bytes:
    payload = pickle.dumps(models, protocol=pickle.HIGHEST_PROTOCOL)
    return MAGIC + zlib.compress(payload, level=1)


def deserialize_models(blob: bytes) -> list[Any]:
    if not blob.startswith(MAGIC):
        raise ValueError("not a predictionio_tpu model blob (bad magic)")
    payload = zlib.decompress(blob[len(MAGIC):])
    return pickle.loads(payload)
