"""WorkflowContext — what DASE components receive instead of a SparkContext.

Reference parity: ``core/.../workflow/WorkflowContext.scala:28-47`` created a
SparkContext per run with a mode tag ("training"/"evaluation"/"serving").
Here the context carries the storage locator, the device mesh the run is
pinned to, the app addressing, and the mode. It is cheap to construct;
nothing opens until used.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from jax.sharding import Mesh

    from predictionio_tpu.data.storage.registry import Storage


@dataclasses.dataclass
class WorkflowContext:
    mode: str = "training"  # training | evaluation | serving
    app_name: str | None = None
    channel_name: str | None = None
    batch: str = ""
    _storage: "Storage | None" = None
    _mesh: "Mesh | None" = None
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def storage(self) -> "Storage":
        if self._storage is None:
            from predictionio_tpu.data.storage.registry import Storage

            self._storage = Storage.instance()
        return self._storage

    @property
    def mesh(self) -> "Mesh":
        if self._mesh is None:
            from predictionio_tpu.parallel.mesh import local_mesh

            self._mesh = local_mesh()
        return self._mesh

    def with_mode(self, mode: str) -> "WorkflowContext":
        return dataclasses.replace(self, mode=mode)

    # Engine-facing store accessors (what templates actually use)
    def p_event_store(self):
        from predictionio_tpu.data.store.event_store import PEventStore

        return PEventStore(self.storage)

    def l_event_store(self):
        from predictionio_tpu.data.store.event_store import LEventStore

        return LEventStore(self.storage)
