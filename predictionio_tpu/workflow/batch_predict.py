"""Device-saturating offline batch prediction (``pio batchpredict``).

Reference parity: ``core/.../workflow/BatchPredict.scala:50-235`` — read a
multi-line JSON query file, re-run the deploy logic per query, write JSON
predictions line-aligned to an output file. The reference parallelized with
an RDD over partitions; the first reproduction walked the whole file through
the per-query serving path. BENCH_r01 measured what that leaves on the
table: 973 qps batched vs 14.6 sequential — the online path (HTTP parsing,
micro-batch admission, per-request accounting) can never saturate the
device, so this module is the dedicated offline path (ROADMAP item 4):

    source ──read──▶ raw queries ──assemble──▶ mega-batch ──dispatch──▶ device
                                                                          │
    sink  ◀──write── served results ◀──fetch── packed [B,2,k] top-k ◀─────┘

- **Streaming sources** — :func:`iter_query_file` reads the query file line
  by line; :func:`iter_event_users` streams DISTINCT users straight off the
  event store in ``find_after`` order (the PR-5 ordering contract, bounded
  pages) and synthesizes ``{"user", "num"}`` queries. Neither materializes
  the corpus on the host.
- **Mega-batch scheduler** — :func:`run_pipeline` assembles fixed
  (pow2-bucketed) batches into the engines' pipelined dispatch entry
  (:meth:`Engine.dispatch_batch` → ``predict_batch_dispatch`` → the fused
  ``ops/topk`` kernels with donated per-batch ScratchBuffers; no HTTP, no
  micro-batcher) and **double-buffers**: while the device computes batch N,
  the host reads+assembles batch N+1 and fetches+writes batch N-1 — neither
  side idles.
- **Writeback sinks** — :class:`FileSink` writes line-aligned JSONL
  atomically (tmp+rename, the registry-store idiom: a killed run never
  leaves a truncated half-file that looks complete); :class:`EventStoreSink`
  streams results into the event-store DAO (memory/JSONL/SQL — whatever the
  storage env selects) behind a PR-2 retry/breaker policy.
- **Evidence** — the whole run records under a PR-7 xray
  :class:`~predictionio_tpu.obs.xray.TrainProfile` whose five phases
  (``read → assemble → dispatch → fetch → write``) TILE the run wall clock
  (same 10% contract as the serving waterfall and the train profiler), and
  a throttled atomic status file feeds the ``pio top --batchpredict``
  progress line while the run is active.

Error contract: a malformed query line becomes a line-aligned JSON error
object ``{"error": ..., "line": N}`` in the output (counted in
``pio_batchpredict_errors_total``) instead of aborting the run; the exit is
nonzero only when *every* line failed.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import statistics
import tempfile
import time
from typing import Any, Callable, Iterable, Iterator

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.data.storage.base import LEvents, event_seq_key
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import xray
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.resilience import CircuitBreaker, RetryPolicy
from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.core_workflow import load_models_for_instance
from predictionio_tpu.workflow.engine_loader import load_engine

logger = logging.getLogger(__name__)

# the offline phase vocabulary — tiles the run wall clock (docs/batch_predict.md)
PHASE_READ = "read"  # pulling the next query from the source (file IO / event-store paging)
PHASE_ASSEMBLE = "assemble"  # JSON parse + engine query decode into the pending batch
PHASE_DISPATCH = "dispatch"  # supplement + device upload + fused-kernel launch
PHASE_FETCH = "fetch"  # packed [B,2,k] fetch + decode + serve
PHASE_WRITE = "write"  # result encode + sink write (file/event-store)

BATCH_PHASES: tuple[str, ...] = (
    PHASE_READ,
    PHASE_ASSEMBLE,
    PHASE_DISPATCH,
    PHASE_FETCH,
    PHASE_WRITE,
)

DEFAULT_MEGA_BATCH = 512
DEFAULT_EVENT_PAGE = 2048
DEFAULT_RESULT_EVENT = "batchpredict.result"


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------


def register_batchpredict_metrics(registry: MetricsRegistry) -> dict[str, Any]:
    """Get-or-create the ``pio_batchpredict_*`` family (idempotent) — the
    offline twin of the serving counters, exported through the run's
    status file and any registry a caller shares in."""
    return {
        "queries": registry.counter(
            "pio_batchpredict_queries_total",
            "offline queries pulled from the source (ok + errored)",
        ),
        "errors": registry.counter(
            "pio_batchpredict_errors_total",
            "query lines that failed (malformed JSON, decode or batch "
            "failure) — each emitted as a line-aligned error object",
        ),
        "batches": registry.counter(
            "pio_batchpredict_batches_total",
            "mega-batches dispatched through the fused kernels",
        ),
        "rows": registry.counter(
            "pio_batchpredict_rows_written_total",
            "result rows streamed to a writeback sink",
            labelnames=("sink",),
        ),
        "write_retries": registry.counter(
            "pio_batchpredict_write_retries_total",
            "writeback attempts retried by the resilience policy",
        ),
        "active": registry.gauge(
            "pio_batchpredict_active",
            "1 while an offline batch-predict run is executing",
        ),
    }


class BatchPredictInstruments:
    """Counter bundle for one offline run (own registry by default)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        m = register_batchpredict_metrics(self.registry)
        self.queries = m["queries"]
        self.errors = m["errors"]
        self.batches = m["batches"]
        self.rows = m["rows"]
        self.write_retries = m["write_retries"]
        self.active = m["active"]


# ---------------------------------------------------------------------------
# streaming query sources
# ---------------------------------------------------------------------------


def iter_query_file(path: str) -> Iterator[tuple[int, Any]]:
    """Stream ``(lineno, raw_json_line)`` from a multi-line query file
    without ever holding more than one line on the host (the old shim's
    ``readlines()`` materialized the whole corpus). Blank lines are
    skipped; line numbers are 1-based file positions so error objects
    stay auditable against the input."""
    with open(path) as fh:
        for lineno, raw in enumerate(fh, start=1):
            if raw.strip():
                yield lineno, raw


def iter_event_users(
    levents: LEvents,
    app_id: int,
    channel_id: int | None = None,
    *,
    num: int = 10,
    entity_type: str = "user",
    page: int = DEFAULT_EVENT_PAGE,
    limit: int = 0,
) -> Iterator[tuple[int, Any]]:
    """Stream DISTINCT ``entity_type`` ids straight off the event store as
    synthesized ``{"user": id, "num": num}`` queries — the
    ``--from-events`` source. Rides the ``find_after`` ordering contract
    (bounded pages, exclusive cursor), so the corpus is never materialized:
    only the dedup id-set (a few bytes per distinct user) lives on the
    host. ``limit`` > 0 caps the distinct users yielded."""
    # bound the scan at the store head AS OF RUN START: a --to-events run
    # inserts its results into the same store, and an unbounded tail would
    # page over its own writeback events (dedup keeps that correct, but
    # the run should mean "every user known when it started", not chase
    # the head it is itself advancing)
    head = levents.seq_head(app_id, channel_id)
    if head is None:
        return
    cursor: tuple[int, str] | None = None
    seen: set[str] = set()
    row = 0
    while True:
        events = levents.find_after(
            app_id, channel_id=channel_id, cursor=cursor, limit=page
        )
        if not events:
            return
        cursor = event_seq_key(events[-1])
        for e in events:
            if event_seq_key(e) > head:
                return
            if e.entity_type != entity_type or not e.entity_id:
                continue
            if e.entity_id in seen:
                continue
            seen.add(e.entity_id)
            row += 1
            yield row, {"user": e.entity_id, "num": num}
            if limit and row >= limit:
                return


# ---------------------------------------------------------------------------
# writeback sinks
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OutRow:
    """One line-aligned output row: a served result or an error object."""

    lineno: int
    query: Any  # the decoded query (None when the line never parsed)
    result: dict[str, Any]  # encode_result output, or {"error", "line"}
    ok: bool


class BatchPredictSink:
    """Streaming writeback target: ``write_batch`` per mega-batch, then
    ``close(success)`` exactly once. ``close(False)`` must leave no
    half-written artifact behind."""

    name = "sink"

    def write_batch(self, rows: list[OutRow]) -> None:
        raise NotImplementedError

    def close(self, success: bool) -> None:  # noqa: B027 - optional hook
        pass


class FileSink(BatchPredictSink):
    """Line-aligned JSONL output written ATOMICALLY: rows stream into a
    tmp file in the destination directory and ``os.replace`` publishes it
    only on successful close — the registry-store idiom, so a killed run
    never leaves a truncated half-file that looks complete."""

    name = "file"

    def __init__(self, path: str):
        self.path = path
        directory = os.path.dirname(os.path.abspath(path))
        fd, self._tmp = tempfile.mkstemp(
            dir=directory, prefix=".tmp-batchpredict-"
        )
        self._fh = os.fdopen(fd, "w")
        self._closed = False

    def write_batch(self, rows: list[OutRow]) -> None:
        for row in rows:
            self._fh.write(json.dumps(row.result, sort_keys=True) + "\n")

    def close(self, success: bool) -> None:
        if self._closed:
            return
        self._closed = True
        if not success:
            try:
                self._fh.close()
            finally:
                with contextlib.suppress(OSError):
                    os.unlink(self._tmp)
            return
        # publish ONLY after flush+fsync+close all succeeded: a failed
        # flush (disk full) must leave the destination untouched, never
        # install a truncated file that looks complete
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
        except BaseException:
            with contextlib.suppress(OSError):
                self._fh.close()
            with contextlib.suppress(OSError):
                os.unlink(self._tmp)
            raise
        os.replace(self._tmp, self.path)


class EventStoreSink(BatchPredictSink):
    """Stream scored top-k rows into the event-store DAO (whatever backend
    the storage env selects: memory, JSONL, SQL, ...) behind a PR-2
    retry/breaker policy — one ``insert_batch`` per mega-batch. Error rows
    have no entity to attach to and are skipped (they still reach the file
    sink and the error counter)."""

    name = "events"

    def __init__(
        self,
        levents: LEvents,
        app_id: int,
        channel_id: int | None = None,
        event_name: str = DEFAULT_RESULT_EVENT,
        model_version: str = "",
        retry: RetryPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        on_retry: Callable[[], None] | None = None,
    ):
        self._levents = levents
        self._app_id = app_id
        self._channel_id = channel_id
        self._event_name = event_name
        self._model_version = model_version
        self._retry = retry or RetryPolicy(
            max_attempts=3,
            on_retry=(lambda *_a, **_k: on_retry()) if on_retry else None,
        )
        self._breaker = breaker or CircuitBreaker(name="batchpredict.writeback")

    def write_batch(self, rows: list[OutRow]) -> None:
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event

        events = []
        for row in rows:
            if not row.ok:
                continue
            user = getattr(row.query, "user", None)
            if user is None and isinstance(row.query, dict):
                user = row.query.get("user")
            events.append(
                Event(
                    event=self._event_name,
                    entity_type="user",
                    entity_id=str(user) if user is not None else f"line{row.lineno}",
                    properties=DataMap(
                        {
                            "prediction": row.result,
                            "modelVersion": self._model_version,
                            "line": row.lineno,
                        }
                    ),
                )
            )
        if not events:
            return

        def _insert():
            return self._breaker.call(
                self._levents.insert_batch, events, self._app_id, self._channel_id
            )

        self._retry.call(_insert)


class MemorySink(BatchPredictSink):
    """Collects rows in memory — tests and the pure-core compat path."""

    name = "memory"

    def __init__(self):
        self.rows: list[OutRow] = []

    def write_batch(self, rows: list[OutRow]) -> None:
        self.rows.extend(rows)


# ---------------------------------------------------------------------------
# progress status file (pio top --batchpredict)
# ---------------------------------------------------------------------------


class StatusFile:
    """Throttled atomic progress snapshots: ``pio top --batchpredict``
    renders the latest write while the run is active, and the final
    ``state: done`` record survives the process for post-hoc evidence."""

    def __init__(
        self,
        path: str,
        interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.path = path
        self.interval_s = interval_s
        self._clock = clock
        self._last = float("-inf")
        self.fields: dict[str, Any] = {
            "state": "starting",
            "pid": os.getpid(),
            "startedUnix": time.time(),
        }

    def update(self, force: bool = False, **fields: Any) -> None:
        self.fields.update(fields)
        now = self._clock()
        if not force and now - self._last < self.interval_s:
            return
        self._last = now
        self.fields["updatedUnix"] = time.time()
        directory = os.path.dirname(os.path.abspath(self.path)) or "."
        try:
            fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-status-")
            with os.fdopen(fd, "w") as fh:
                json.dump(self.fields, fh)
            os.replace(tmp, self.path)
        except OSError:  # progress evidence must never kill the run
            logger.warning("batchpredict status write failed", exc_info=True)


# ---------------------------------------------------------------------------
# the mega-batch pipeline
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Row:
    lineno: int
    query: Any
    error: str | None = None


@dataclasses.dataclass
class BatchPredictReport:
    """One run's evidence: counts, throughput, and the phase timeline."""

    queries: int = 0
    ok: int = 0
    errors: int = 0
    batches: int = 0
    distinct_users: int = 0
    batch_size: int = 0
    wall_s: float = 0.0
    warmup_s: float = 0.0
    qps: float = 0.0
    users_per_s: float = 0.0
    tiling_ratio: float = 0.0
    phase_p50_ms: dict[str, float] = dataclasses.field(default_factory=dict)
    phase_total_s: dict[str, float] = dataclasses.field(default_factory=dict)
    profile: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def all_failed(self) -> bool:
        return self.queries > 0 and self.ok == 0

    def to_json_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["allFailed"] = self.all_failed
        return d


class _PhaseClock:
    """Times a block into BOTH the xray profile (tiling contract) and a
    per-phase sample list (per-batch p50s) — one timing source, two
    consumers. Double-buffering splits a batch's phases across loop
    iterations, so the profile's per-*step* timeline can't align with
    batches; the sample lists restore per-batch percentiles."""

    def __init__(self, profile: xray.TrainProfile):
        self.profile = profile
        self.samples: dict[str, list[float]] = {p: [] for p in BATCH_PHASES}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        with self.profile.phase(name):
            yield
        self.samples.setdefault(name, []).append(time.perf_counter() - t0)

    def p50_ms(self) -> dict[str, float]:
        return {
            name: round(statistics.median(vals) * 1000.0, 4)
            for name, vals in self.samples.items()
            if vals
        }


def _assemble_batches(
    source: Iterable[tuple[int, Any]],
    engine: Engine,
    batch_size: int,
    clock: _PhaseClock,
    instruments: BatchPredictInstruments,
) -> Iterator[list[_Row]]:
    """read + assemble: pull one mega-batch's queries from the source,
    decode them, yield. A malformed line becomes an errored row (counted),
    never an abort. Phase accounting is per BATCH, not per item — per-item
    context managers cost ~10µs of unattributed clock each, which at 20k+
    queries visibly breaks the tiling contract."""
    it = iter(source)
    done = False
    while not done:
        raw: list[tuple[int, Any]] = []
        with clock.phase(PHASE_READ):
            while len(raw) < batch_size:
                item = next(it, None)
                if item is None:
                    done = True
                    break
                raw.append(item)
        if not raw:
            return
        rows: list[_Row] = []
        with clock.phase(PHASE_ASSEMBLE):
            for lineno, payload in raw:
                instruments.queries.inc()
                try:
                    obj = (
                        json.loads(payload)
                        if isinstance(payload, str)
                        else payload
                    )
                    rows.append(_Row(lineno, engine.decode_query(obj)))
                except Exception as exc:  # noqa: BLE001 - line-aligned error object
                    instruments.errors.inc()
                    rows.append(
                        _Row(lineno, None, error=f"{type(exc).__name__}: {exc}")
                    )
        yield rows


def run_pipeline(
    engine: Engine,
    components: tuple,
    models: list,
    source: Iterable[tuple[int, Any]],
    sinks: list[BatchPredictSink],
    batch_size: int = DEFAULT_MEGA_BATCH,
    instruments: BatchPredictInstruments | None = None,
    status: StatusFile | None = None,
    warmup: bool = True,
) -> BatchPredictReport:
    """Drive the full offline pipeline; returns the run report.

    Double-buffering: iteration N dispatches batch N's device work
    (async — ``predict_batch_dispatch`` returns before the kernel
    finishes), then drains batch N-1 (fetch + write) while the device
    computes N, and the generator assembles N+1 between drains. Host and
    device overlap; the phase clock keeps the evidence honest."""
    _, _, algorithms, serving = components
    instruments = instruments or BatchPredictInstruments()
    report = BatchPredictReport(batch_size=batch_size)
    profile = xray.TrainProfile(
        trainer="batchpredict",
        registry=instruments.registry,
        timeline_cap=4096,
    )
    clock = _PhaseClock(profile)

    t0 = time.perf_counter()
    if warmup:
        # compile every pow2 bucket up to the mega-batch size BEFORE the
        # measured window: XLA compiles are a one-time cost and must not
        # smear the steady-state throughput evidence
        for algo, model in zip(algorithms, models):
            with contextlib.suppress(Exception):
                algo.warmup_serving(model, batch_size)
    report.warmup_s = round(time.perf_counter() - t0, 4)

    instruments.active.set(1.0)
    if status is not None:
        status.update(force=True, state="running", batchSize=batch_size)

    # distinct-user accounting for the users/s evidence field (id strings
    # only — same order of host memory as the --from-events dedup set)
    users_seen: set[Any] = set()

    def drain(pending: tuple[Callable[[], list] | None, list[_Row]]) -> None:
        fin, rows = pending
        served: list[Any] = []
        batch_error: str | None = None
        if fin is not None:
            with clock.phase(PHASE_FETCH):
                try:
                    served = fin()
                except Exception as exc:  # noqa: BLE001 - batch fails, run survives
                    batch_error = f"{type(exc).__name__}: {exc}"
                    logger.exception("mega-batch finalize failed")
        with clock.phase(PHASE_WRITE):
            out: list[OutRow] = []
            it = iter(served)
            for row in rows:
                if row.error is not None:
                    out.append(
                        OutRow(
                            row.lineno,
                            row.query,
                            {"error": row.error, "line": row.lineno},
                            ok=False,
                        )
                    )
                elif batch_error is not None:
                    instruments.errors.inc()
                    out.append(
                        OutRow(
                            row.lineno,
                            row.query,
                            {"error": batch_error, "line": row.lineno},
                            ok=False,
                        )
                    )
                else:
                    result = Engine.encode_result(next(it))
                    report.ok += 1
                    out.append(OutRow(row.lineno, row.query, result, ok=True))
            for sink in sinks:
                sink.write_batch(out)
                instruments.rows.inc(len(out), sink=sink.name)
        for r in rows:
            if r.query is not None:
                user = getattr(r.query, "user", None)
                if user is None and isinstance(r.query, dict):
                    user = r.query.get("user")
                if user is not None:
                    users_seen.add(user)
        report.queries += len(rows)
        report.errors += sum(1 for r in out if not r.ok)
        report.batches += 1
        instruments.batches.inc()
        profile.add_rows(len(rows))
        if status is not None:
            wall = profile.wall_s
            status.update(
                queries=report.queries,
                ok=report.ok,
                errors=report.errors,
                batches=report.batches,
                qps=round(report.queries / wall, 1) if wall > 0 else 0.0,
            )

    success = False
    try:
        with profile.measure():
            pending: tuple[Callable[[], list] | None, list[_Row]] | None = None
            for rows in _assemble_batches(
                source, engine, batch_size, clock, instruments
            ):
                queries = [r.query for r in rows if r.error is None]
                fin = None
                if queries:
                    with clock.phase(PHASE_DISPATCH):
                        try:
                            fin = engine.dispatch_batch(
                                algorithms, serving, models, queries
                            )
                        except Exception as exc:  # noqa: BLE001
                            logger.exception("mega-batch dispatch failed")
                            err = f"{type(exc).__name__}: {exc}"
                            for r in rows:
                                if r.error is None:
                                    r.error = err
                                    instruments.errors.inc()
                if pending is not None:
                    drain(pending)
                pending = (fin, rows)
            if pending is not None:
                drain(pending)
        success = True
    finally:
        profile.finish()
        instruments.active.set(0.0)
        for sink in sinks:
            if success:
                sink.close(True)  # a failed atomic publish must surface
            else:
                # already unwinding: cleanup must not mask the original
                with contextlib.suppress(Exception):
                    sink.close(False)

    report.wall_s = round(profile.wall_s, 4)
    report.qps = (
        round(report.queries / report.wall_s, 2) if report.wall_s > 0 else 0.0
    )
    report.distinct_users = len(users_seen)
    # DISTINCT users precomputed per second — diverges from qps when the
    # query stream repeats users (or carries none: item-set queries
    # report 0). The canonical --from-events nightly run is one query
    # per user, where the two coincide.
    report.users_per_s = (
        round(report.distinct_users / report.wall_s, 2)
        if report.wall_s > 0
        else 0.0
    )
    report.tiling_ratio = (
        round(profile.attributed_s / profile.wall_s, 4)
        if profile.wall_s > 0
        else 0.0
    )
    report.phase_p50_ms = clock.p50_ms()
    report.phase_total_s = {
        name: round(agg.wall_s, 4) for name, agg in sorted(profile.phases.items())
    }
    report.profile = profile.to_json_dict()
    if status is not None:
        status.update(
            force=True,
            state="done" if success and not report.all_failed else "failed",
            queries=report.queries,
            ok=report.ok,
            errors=report.errors,
            batches=report.batches,
            qps=report.qps,
            phaseP50Ms=report.phase_p50_ms,
            wallS=report.wall_s,
        )
    return report


# ---------------------------------------------------------------------------
# compat pure core + file-level entry
# ---------------------------------------------------------------------------


def run_batch_predict_on(
    engine: Engine,
    engine_params: EngineParams,
    models: list,
    queries: Iterable[str],
) -> list[str]:
    """Pure core (kept for API parity): JSON query lines in, JSON
    prediction lines out — now routed through the mega-batch pipeline."""
    components = engine.make_components(engine_params)
    sink = MemorySink()
    source = (
        (i, line)
        for i, line in enumerate(queries, start=1)
        if line.strip()
    )
    run_pipeline(
        engine, components, models, source, [sink], warmup=False
    )
    return [json.dumps(r.result, sort_keys=True) for r in sink.rows]


def run_batch_predict(
    engine_dir: str,
    input_path: str | None = None,
    output_path: str | None = None,
    variant_path: str | None = None,
    storage: Storage | None = None,
    instance_id: str | None = None,
    *,
    from_events: bool = False,
    app_name: str = "",
    channel: str = "",
    query_num: int = 10,
    to_events: bool = False,
    event_name: str = DEFAULT_RESULT_EVENT,
    batch_size: int = DEFAULT_MEGA_BATCH,
    limit: int = 0,
    status_path: str | None = None,
    instruments: BatchPredictInstruments | None = None,
) -> BatchPredictReport:
    """File-level entry (ref BatchPredict.run), rebuilt on the pipeline.

    Sources: ``input_path`` (default) or ``from_events`` (stream distinct
    users off the app's event store). Sinks: ``output_path`` (atomic
    line-aligned JSONL) and/or ``to_events`` (event-store writeback).
    Returns the run report; raising is reserved for setup failures — a
    failing query line is an error *row*, not an exception."""
    storage = storage or Storage.instance()
    manifest, engine = load_engine(engine_dir, variant_path)
    instances = storage.get_meta_data_engine_instances()
    instance = (
        instances.get(instance_id)
        if instance_id
        else instances.get_latest_completed(
            manifest.engine_id, manifest.version, manifest.variant
        )
    )
    if instance is None:
        raise RuntimeError("no COMPLETED engine instance; run train first")
    engine_params = engine.engine_params_from_variant(manifest.variant_json)
    ctx = WorkflowContext(mode="serving", _storage=storage)
    models = load_models_for_instance(
        engine, engine_params, instance.id, ctx=ctx, storage=storage
    )
    components = engine.make_components(engine_params)

    # --from-events / --to-events need the app; default to the variant's
    # datasource appName so the CLI matches `pio train`'s resolution
    app_id = channel_id = None
    if from_events or to_events:
        app_name = app_name or getattr(
            components[0].params, "app_name", ""
        )
        if not app_name:
            raise RuntimeError(
                "--from-events/--to-events need --app-name (or a datasource "
                "appName in the engine variant)"
            )
        app = storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            raise RuntimeError(f"app not found: {app_name}")
        app_id = app.id
        if channel:
            chans = storage.get_meta_data_channels().get_by_app_id(app_id)
            match = [c for c in chans if c.name == channel]
            if not match:
                raise RuntimeError(f"channel not found: {channel}")
            channel_id = match[0].id

    levents = storage.get_l_events() if (from_events or to_events) else None
    if from_events:
        source: Iterable[tuple[int, Any]] = iter_event_users(
            levents, app_id, channel_id, num=query_num, limit=limit
        )
    else:
        if not input_path:
            raise RuntimeError("need an --input query file or --from-events")
        if not os.path.isfile(input_path):
            # check EAGERLY: iter_query_file defers open() to the first
            # generator pull inside the pipeline — a missing file is a
            # setup error (docstring contract), not a mid-run one
            raise RuntimeError(f"query file not found: {input_path}")
        source = iter_query_file(input_path)
        if limit:
            source = _take(source, limit)

    instruments = instruments or BatchPredictInstruments()
    sinks: list[BatchPredictSink] = []
    if output_path:
        sinks.append(FileSink(output_path))
    if to_events:
        sinks.append(
            EventStoreSink(
                levents,
                app_id,
                channel_id,
                event_name=event_name,
                model_version=instance.id,
                on_retry=instruments.write_retries.inc,
            )
        )
    if not sinks:
        raise RuntimeError("need an --output file and/or --to-events")

    status = (
        StatusFile(status_path) if status_path else None
    )
    if status is not None:
        status.update(
            force=True,
            engineId=manifest.engine_id,
            instanceId=instance.id,
            source="events" if from_events else (input_path or ""),
            output=output_path or "",
        )
    report = run_pipeline(
        engine,
        components,
        models,
        source,
        sinks,
        batch_size=batch_size,
        instruments=instruments,
        status=status,
    )
    logger.info(
        "batch predict: %d queries (%d ok, %d errors) in %.2fs (%.0f q/s) -> %s",
        report.queries,
        report.ok,
        report.errors,
        report.wall_s,
        report.qps,
        ", ".join(s.name for s in sinks),
    )
    return report


def _take(
    source: Iterable[tuple[int, Any]], limit: int
) -> Iterator[tuple[int, Any]]:
    for n, item in enumerate(source):
        if n >= limit:
            return
        yield item
