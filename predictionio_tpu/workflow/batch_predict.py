"""Offline batch prediction.

Reference parity: ``core/.../workflow/BatchPredict.scala:50-235`` — read a
multi-line JSON query file, re-run the deploy logic per query (supplement ->
predict per algorithm -> serve), write JSON predictions line-aligned to an
output file. The reference parallelized with an RDD over partitions; here
queries are batched through the algorithms' (possibly vectorized)
``batch_predict`` so a jitted predict path sees real batches instead of one
query at a time.
"""

from __future__ import annotations

import json
import logging
from typing import Iterable

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.core_workflow import load_models_for_instance
from predictionio_tpu.workflow.engine_loader import load_engine

logger = logging.getLogger(__name__)


def run_batch_predict_on(
    engine: Engine,
    engine_params: EngineParams,
    models: list,
    queries: Iterable[str],
) -> list[str]:
    """Pure core: JSON query lines in, JSON prediction lines out."""
    _, _, algorithms, serving = engine.make_components(engine_params)
    parsed = []
    for line in queries:
        line = line.strip()
        if not line:
            continue
        parsed.append(engine.decode_query(json.loads(line)))
    supplemented = [(i, serving.supplement(q)) for i, q in enumerate(parsed)]
    per_query: list[list] = [[] for _ in parsed]
    for algo, model in zip(algorithms, models):
        for i, p in algo.batch_predict(model, supplemented):
            per_query[i].append(p)
    out = []
    for i, preds in enumerate(per_query):
        result = serving.serve(parsed[i], preds)
        out.append(json.dumps(Engine.encode_result(result), sort_keys=True))
    return out


def run_batch_predict(
    engine_dir: str,
    input_path: str,
    output_path: str,
    variant_path: str | None = None,
    storage: Storage | None = None,
    instance_id: str | None = None,
) -> int:
    """File-level entry (ref BatchPredict.run). Returns #queries predicted."""
    storage = storage or Storage.instance()
    manifest, engine = load_engine(engine_dir, variant_path)
    instances = storage.get_meta_data_engine_instances()
    instance = (
        instances.get(instance_id)
        if instance_id
        else instances.get_latest_completed(
            manifest.engine_id, manifest.version, manifest.variant
        )
    )
    if instance is None:
        raise RuntimeError("no COMPLETED engine instance; run train first")
    engine_params = engine.engine_params_from_variant(manifest.variant_json)
    ctx = WorkflowContext(mode="serving", _storage=storage)
    models = load_models_for_instance(
        engine, engine_params, instance.id, ctx=ctx, storage=storage
    )
    with open(input_path) as f:
        lines = f.readlines()
    results = run_batch_predict_on(engine, engine_params, models, lines)
    with open(output_path, "w") as f:
        for line in results:
            f.write(line + "\n")
    logger.info("batch predict: %d queries -> %s", len(results), output_path)
    return len(results)
