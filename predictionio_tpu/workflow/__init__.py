"""Workflow engine: train/eval/deploy/batch-predict orchestration.

Reference parity: ``core/.../workflow/`` — ``CreateWorkflow`` (train/eval
main), ``CoreWorkflow`` (train persistence), ``CreateServer`` (deploy),
``BatchPredict``, ``WorkflowUtils``, ``CleanupFunctions``.
"""

from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.cleanup import CleanupFunctions

__all__ = ["WorkflowContext", "CleanupFunctions"]
