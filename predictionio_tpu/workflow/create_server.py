"""Engine (query) server — the deploy surface.

Reference parity: ``core/.../workflow/CreateServer.scala`` —
  POST /queries.json  (:464-616): decode query -> serving.supplement ->
                      per-algorithm predict -> serving.serve -> JSON;
                      optional async feedback POST of a `predict` event
                      (entityType ``pio_pr``, prId) to the event server
                      (:500-570); per-request latency bookkeeping (:578-585).
  GET /               engine status incl. requestCount / avgServingSec /
                      lastServingSec (:385-420).
  GET /reload         hot-swap to the latest COMPLETED engine instance
                      (MasterActor :317-343).
  POST/GET /stop      graceful undeploy (used by the CLI's undeploy).
  GET /plugins.json   engine-server plugin inventory.

TPU notes: models are re-laid-out on device once at (re)load via
``Engine.prepare_deploy``; the predict path calls resident jitted functions
(e.g. the ALS top-k) so a request does one small host->device transfer and
one device->host top-k readback. Serving latency histogram kept in-process
(the measurement machinery BASELINE.md requires).
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime as _dt
import json
import logging
import time
from typing import Any

from aiohttp import web

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.data.storage.base import EngineInstance
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.core_workflow import load_models_for_instance
from predictionio_tpu.workflow.engine_loader import EngineManifest, load_engine
from predictionio_tpu.utils.histogram import LatencyHistogram

logger = logging.getLogger(__name__)
UTC = _dt.timezone.utc


@dataclasses.dataclass
class ServerConfig:
    ip: str = "0.0.0.0"
    port: int = 8000
    accesskey: str | None = None  # optional auth for /queries.json
    feedback: bool = False
    event_server_url: str | None = None  # e.g. http://localhost:7070
    feedback_access_key: str | None = None
    # TLS (ref common/SSLConfiguration.scala): PEM cert + key paths
    ssl_certfile: str | None = None
    ssl_keyfile: str | None = None
    bind_retries: int = 3  # ref MasterActor bind retry x3 (CreateServer.scala:348)
    # remote log shipping of serving errors (ref CreateServer.scala:423-434,
    # 595-611): POST log_prefix + JSON{engineInstance, message} to log_url
    log_url: str | None = None
    log_prefix: str = ""

    def ssl_context(self):
        if bool(self.ssl_certfile) != bool(self.ssl_keyfile):
            # one without the other would silently serve plaintext
            raise ValueError(
                "TLS misconfigured: both ssl_certfile and ssl_keyfile are required"
            )
        if not self.ssl_certfile:
            return None
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.ssl_certfile, self.ssl_keyfile)
        return ctx


class QueryServer:
    def __init__(
        self,
        engine: Engine,
        engine_params: EngineParams,
        models: list[Any],
        manifest: EngineManifest,
        instance_id: str,
        storage: Storage | None = None,
        config: ServerConfig | None = None,
        plugin_context=None,
    ):
        from predictionio_tpu.workflow.server_plugins import (
            EngineServerPluginContext,
        )

        self.engine = engine
        self.engine_params = engine_params
        self.manifest = manifest
        self.instance_id = instance_id
        self.storage = storage or Storage.instance()
        self.config = config or ServerConfig()
        self.plugin_context = plugin_context or EngineServerPluginContext()
        _, _, self.algorithms, self.serving = engine.make_components(engine_params)
        self.models = models
        self.start_time = _dt.datetime.now(tz=UTC)
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0
        self.latency = LatencyHistogram()
        self._runner: web.AppRunner | None = None
        self._stop_event = asyncio.Event()
        # strong refs to fire-and-forget tasks (the loop keeps only weak ones)
        self._bg_tasks: set[asyncio.Task] = set()

    # ---------------------------------------------------------------- routes
    async def handle_queries(self, request: web.Request) -> web.Response:
        if self.config.accesskey:
            supplied = request.query.get("accessKey") or request.headers.get(
                "Authorization", ""
            ).removeprefix("Bearer ").strip()
            if supplied != self.config.accesskey:
                return web.json_response({"message": "Invalid accessKey."}, status=401)
        t0 = time.perf_counter()
        try:
            payload = await request.json()
        except Exception as exc:
            return web.json_response({"message": str(exc)}, status=400)
        try:
            query = self.engine.decode_query(payload)
            supplemented = self.serving.supplement(query)
            predictions = [
                algo.predict(model, supplemented)
                for algo, model in zip(self.algorithms, self.models)
            ]
            result = self.serving.serve(query, predictions)
            result = self.plugin_context.apply_output_blockers(
                self.manifest.variant, query, result
            )
            body = Engine.encode_result(result)
            if self.plugin_context.output_sniffers:
                # asynchronous observers: off the request path, result object
                asyncio.get_running_loop().run_in_executor(
                    None,
                    self.plugin_context.notify_output_sniffers,
                    self.manifest.variant,
                    query,
                    result,
                )
        except Exception as exc:
            logger.exception("query failed")
            if self.config.log_url:
                import traceback

                msg = f"Query:\n{payload}\n\nStack Trace:\n{traceback.format_exc()}\n\n"
                self._spawn_bg(self._remote_log(msg))
            return web.json_response({"message": str(exc)}, status=400)
        elapsed = time.perf_counter() - t0
        self.request_count += 1
        self.last_serving_sec = elapsed
        self.avg_serving_sec += (elapsed - self.avg_serving_sec) / self.request_count
        self.latency.observe(elapsed)
        if self.config.feedback:
            self._spawn_bg(self._send_feedback(payload, body))
        return web.json_response(body)

    def _spawn_bg(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    async def _remote_log(self, message: str) -> None:
        """Ship a serving error to the remote collector: POST body is
        ``log_prefix`` + JSON of {engineInstance, message}
        (ref ``CreateServer.remoteLog``, CreateServer.scala:423-434)."""
        import aiohttp

        body = self.config.log_prefix + json.dumps(
            {"engineInstance": self.instance_id, "message": message}
        )
        try:
            async with aiohttp.ClientSession() as session:
                await session.post(self.config.log_url, data=body)
        except Exception:
            logger.error("Unable to send remote log")

    async def _send_feedback(self, query: Any, prediction: Any) -> None:
        """POST a `predict` event back to the event server
        (ref CreateServer.scala:500-570)."""
        url = self.config.event_server_url
        key = self.config.feedback_access_key
        if not url or not key:
            return
        import aiohttp

        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": self.manifest.engine_id,
            "properties": {"query": query, "prediction": prediction},
        }
        try:
            async with aiohttp.ClientSession() as session:
                await session.post(
                    f"{url}/events.json", params={"accessKey": key}, json=event
                )
        except Exception:
            logger.exception("feedback POST failed")

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "alive",
                "engineId": self.manifest.engine_id,
                "engineVersion": self.manifest.version,
                "engineVariant": self.manifest.variant,
                "engineFactory": self.manifest.engine_factory,
                "engineInstanceId": self.instance_id,
                "startTime": self.start_time.isoformat(),
                "requestCount": self.request_count,
                "avgServingSec": self.avg_serving_sec,
                "lastServingSec": self.last_serving_sec,
                "latency": self.latency.summary(),
            }
        )

    async def handle_reload(self, request: web.Request) -> web.Response:
        """Swap in the latest COMPLETED instance (ref MasterActor reload)."""
        instances = self.storage.get_meta_data_engine_instances()
        latest = instances.get_latest_completed(
            self.manifest.engine_id, self.manifest.version, self.manifest.variant
        )
        if latest is None:
            return web.json_response(
                {"message": "no completed engine instance found"}, status=404
            )
        try:
            engine_params = self._engine_params_of(latest)
            models = load_models_for_instance(
                self.engine, engine_params, latest.id, storage=self.storage
            )
        except Exception as exc:
            logger.exception("reload failed")
            return web.json_response({"message": str(exc)}, status=500)
        _, _, self.algorithms, self.serving = self.engine.make_components(
            engine_params
        )
        self.engine_params = engine_params
        self.models = models
        self.instance_id = latest.id
        logger.info("reloaded engine instance %s", latest.id)
        return web.json_response({"message": "Reload successful", "instanceId": latest.id})

    def _engine_params_of(self, instance: EngineInstance) -> EngineParams:
        variant = {
            "datasource": {"params": json.loads(instance.data_source_params or "{}")},
            "preparator": {"params": json.loads(instance.preparator_params or "{}")},
            "algorithms": json.loads(instance.algorithms_params or "[]"),
            "serving": {"params": json.loads(instance.serving_params or "{}")},
        }
        return self.engine.engine_params_from_variant(variant)

    async def handle_stop(self, request: web.Request) -> web.Response:
        self._stop_event.set()
        return web.json_response({"message": "Stopping."})

    async def handle_plugins(self, request: web.Request) -> web.Response:
        return web.json_response(self.plugin_context.to_json_dict())

    # ------------------------------------------------------------------- app
    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_status),
                web.post("/queries.json", self.handle_queries),
                # POST is the reference's contract (CreateServer.scala:618-626);
                # GET kept as a browser convenience
                web.post("/reload", self.handle_reload),
                web.get("/reload", self.handle_reload),
                web.post("/stop", self.handle_stop),
                web.get("/stop", self.handle_stop),
                web.get("/plugins.json", self.handle_plugins),
            ]
        )
        return app

    async def start(self) -> None:
        retries = max(1, self.config.bind_retries)
        last_error: Exception | None = None
        for attempt in range(retries):
            # fresh runner+site per attempt: a TCPSite cannot be re-started
            # after a failed bind (it stays registered with the runner)
            self._runner = web.AppRunner(self.make_app())
            await self._runner.setup()
            site = web.TCPSite(
                self._runner,
                self.config.ip,
                self.config.port,
                ssl_context=self.config.ssl_context(),
            )
            try:
                await site.start()
                break
            except OSError as exc:  # bind retry (ref MasterActor x3)
                last_error = exc
                await self._runner.cleanup()
                self._runner = None
                logger.warning(
                    "bind %s:%d failed (attempt %d/%d): %s",
                    self.config.ip,
                    self.config.port,
                    attempt + 1,
                    retries,
                    exc,
                )
                if attempt + 1 < retries:
                    await asyncio.sleep(1.0)
        else:
            raise last_error  # type: ignore[misc]
        logger.info("engine server on %s:%d", self.config.ip, self.config.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def run_until_stopped(self) -> None:
        await self.start()
        await self._stop_event.wait()
        await self.stop()


def create_query_server(
    engine_dir: str,
    variant_path: str | None = None,
    storage: Storage | None = None,
    config: ServerConfig | None = None,
    instance_id: str | None = None,
) -> QueryServer:
    """Resolve the latest COMPLETED instance for the engine dir and build a
    server (ref commands/Engine.deploy :207-242)."""
    storage = storage or Storage.instance()
    manifest, engine = load_engine(engine_dir, variant_path)
    instances = storage.get_meta_data_engine_instances()
    if instance_id:
        instance = instances.get(instance_id)
        if instance is None:
            raise RuntimeError(f"engine instance {instance_id} not found")
    else:
        instance = instances.get_latest_completed(
            manifest.engine_id, manifest.version, manifest.variant
        )
        if instance is None:
            raise RuntimeError(
                f"no COMPLETED engine instance for {manifest.engine_id} "
                f"{manifest.version} {manifest.variant}; run train first"
            )
    engine_params = engine.engine_params_from_variant(manifest.variant_json)
    ctx = WorkflowContext(mode="serving", _storage=storage)
    models = load_models_for_instance(
        engine, engine_params, instance.id, ctx=ctx, storage=storage
    )
    return QueryServer(
        engine=engine,
        engine_params=engine_params,
        models=models,
        manifest=manifest,
        instance_id=instance.id,
        storage=storage,
        config=config,
    )


def run_query_server(
    engine_dir: str,
    variant_path: str | None = None,
    config: ServerConfig | None = None,
) -> None:
    server = create_query_server(engine_dir, variant_path, config=config)

    async def main():
        await server.run_until_stopped()

    asyncio.run(main())
