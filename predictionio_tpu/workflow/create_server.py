"""Engine (query) server — the deploy surface.

Reference parity: ``core/.../workflow/CreateServer.scala`` —
  POST /queries.json  (:464-616): decode query -> serving.supplement ->
                      per-algorithm predict -> serving.serve -> JSON;
                      optional async feedback POST of a `predict` event
                      (entityType ``pio_pr``, prId) to the event server
                      (:500-570); per-request latency bookkeeping (:578-585).
  GET /               engine status incl. requestCount / avgServingSec /
                      lastServingSec (:385-420).
  POST /reload        hot-swap to the latest COMPLETED engine instance
                      (MasterActor :317-343; the GET spelling is kept for
                      compat but logs a deprecation warning).
  GET /models + POST /models/{candidate,promote,rollback}
                      model registry / progressive rollout surface
                      (docs/model_registry.md): pinned stable version,
                      sticky canary or shadow candidate, metric-gated
                      auto-promote and auto-rollback.
  POST/GET /stop      graceful undeploy (used by the CLI's undeploy).
  GET /plugins.json   engine-server plugin inventory.

TPU notes: models are re-laid-out on device once at (re)load via
``Engine.prepare_deploy``; the predict path calls resident jitted functions
(e.g. the ALS top-k) so a request does one small host->device transfer and
one device->host top-k readback. Serving latency histogram kept in-process
(the measurement machinery BASELINE.md requires).
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime as _dt
import json
import logging
import os
import threading
import time
from typing import Any

from aiohttp import web

from predictionio_tpu.ann import lifecycle as ann_lifecycle
from predictionio_tpu.ann.metrics import AnnInstruments
from predictionio_tpu.bandit import (
    ARM_CANDIDATE,
    ARM_STABLE,
    DECIDE_PROMOTE,
    DECIDE_RETIRE,
    BanditCriteria,
    BanditInstruments,
    BanditLoop,
    RewardTailer,
)
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.data.storage.base import EngineInstance
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs.jaxprof import CompileWatcher
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.profiler import (
    ProfileBusyError,
    ProfileSession,
    ProfileStore,
)
from predictionio_tpu.obs.sampler import HostSampler
from predictionio_tpu.obs.tracing import (
    TRACE_HEADER,
    Tracer,
    current_trace_id,
    get_tracer,
    mint_trace_id,
    reset_trace_id,
    set_trace_id,
)
from predictionio_tpu.obs.slo import (
    SLOEngine,
    counter_ratio_source,
    histogram_threshold_source,
    paired_counter_source,
)
from predictionio_tpu.obs.waterfall import (
    PHASE_BATCH_ASSEMBLY,
    PHASE_CACHE,
    PHASE_DEVICE_COMPUTE,
    PHASE_DISPATCH,
    PHASE_FETCH,
    PHASE_INGRESS_PARSE,
    PHASE_QUEUE_WAIT,
    PHASE_RESPOND,
    PHASE_SERVE,
    PhaseWaterfall,
    phase_tags_ms,
)
from predictionio_tpu.obs.web import (
    BreakerInstruments,
    metrics_response,
    slo_response,
    traces_response,
)
from predictionio_tpu.registry.controller import (
    VERDICT_PROMOTE,
    VERDICT_ROLLBACK,
    PromotionCriteria,
    RolloutController,
)
from predictionio_tpu.registry.router import (
    LANE_CANDIDATE,
    LANE_SHADOW,
    LANE_STABLE,
    PLAN_OFF,
    Lane,
    RolloutInstruments,
    RolloutPlan,
    choose_lane,
    routing_key,
)
from predictionio_tpu.registry.result_cache import ResultCache
from predictionio_tpu.registry.store import (
    MODE_CANARY,
    MODE_SHADOW,
    ArtifactStore,
)
from predictionio_tpu.resilience import (
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
)
from predictionio_tpu.workflow import model_io
from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.core_workflow import load_models_for_instance
from predictionio_tpu.workflow.engine_loader import EngineManifest, load_engine

logger = logging.getLogger(__name__)
UTC = _dt.timezone.utc


class LoadShedError(RuntimeError):
    """Admission control rejected the request (queue over high water).

    Not transient in-process: the server is telling the *client* to back
    off (`Retry-After`), not asking itself to retry into the same queue.
    """

    transient = False

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ShuttingDownError(RuntimeError):
    """The server is stopping; in-flight and new requests answer 503."""

    transient = False

    def __init__(self):
        super().__init__("query server is shutting down")


@dataclasses.dataclass
class ServerConfig:
    ip: str = "0.0.0.0"
    port: int = 8000
    accesskey: str | None = None  # optional auth for /queries.json
    feedback: bool = False
    event_server_url: str | None = None  # e.g. http://localhost:7070
    feedback_access_key: str | None = None
    # TLS (ref common/SSLConfiguration.scala): PEM cert + key paths
    ssl_certfile: str | None = None
    ssl_keyfile: str | None = None
    bind_retries: int = 3  # ref MasterActor bind retry x3 (CreateServer.scala:348)
    # remote log shipping of serving errors (ref CreateServer.scala:423-434,
    # 595-611): POST log_prefix + JSON{engineInstance, message} to log_url
    log_url: str | None = None
    log_prefix: str = ""
    # serving micro-batch dispatch: concurrent /queries.json requests are
    # coalesced into one algorithm.predict_batch call (the reference predicts
    # per-request on an actor and carries a literal ``TODO: Parallelize``,
    # CreateServer.scala:488-491). max_batch_size <= 1 disables coalescing;
    # batch_window_ms > 0 adds a flush timer (rarely needed: batches form
    # adaptively while the previous batch is in flight on the worker thread).
    max_batch_size: int = 128
    batch_window_ms: float = 0.0
    # -- resilience (see docs/resilience.md) --------------------------------
    # per-request deadline: a /queries.json answer is due within this many
    # seconds or the request is failed with 503 instead of hanging; <= 0
    # disables (NOT recommended: a wedged device call then blocks forever)
    request_timeout_s: float = 10.0
    # admission control: when this many queries are already waiting in the
    # micro-batch queue, new arrivals are shed with 503 + Retry-After
    # instead of growing the queue without bound; 0 = unbounded
    queue_high_water: int = 256
    shed_retry_after_s: float = 1.0  # Retry-After hint on load-shed 503s
    # oversized request bodies are rejected with 413 before JSON decode
    max_payload_bytes: int = 1 << 20
    # background HTTP (feedback + remote log) total timeout: a stalled
    # collector must not accumulate hung tasks forever
    http_timeout_s: float = 10.0
    # dispatch circuit breaker: this many consecutive watchdog trips (device
    # calls blowing their deadline) opens the circuit and sheds all traffic
    # for breaker_recovery_s before probing again
    breaker_threshold: int = 3
    breaker_recovery_s: float = 5.0
    # -- model registry / progressive rollout (docs/model_registry.md) ------
    # artifact registry base dir; None disables the registry surface (the
    # metadata store's latest-COMPLETED instance is then the only source)
    registry_dir: str | None = None
    # sticky canary routing: the payload field identifying the user (a
    # user must see ONE model for a whole bake); missing fields fall back
    # to a deterministic hash of the payload
    sticky_key_field: str = "user"
    # consecutive candidate-lane failures that trip the candidate breaker
    # and force an INSTANT rollback (no bake-window wait)
    candidate_breaker_threshold: int = 3
    # promotion gates (see registry/controller.py PromotionCriteria)
    bake_window_s: float = 60.0
    bake_min_requests: int = 20
    max_error_ratio: float = 2.0
    max_p95_ratio: float = 1.5
    max_divergence_rate: float = 0.25
    auto_promote: bool = True
    bake_check_interval_s: float = 1.0  # controller evaluation cadence
    # shadow scoring backlog bound (batches): a candidate slower than live
    # traffic drops shadow samples (counted) instead of growing the queue
    # without limit — shadow is sampling, not accounting
    shadow_max_backlog: int = 8
    # -- SLOs (docs/observability.md): burn rates on /slo + pio_slo_* ------
    # latency objective: this fraction of /queries.json answers must land
    # at or under the threshold (default = the paper's <10ms p50 deploy
    # target; keep the threshold on a histogram bucket bound)
    slo_latency_threshold_s: float = 0.010
    slo_latency_objective: float = 0.50
    # availability objective: non-5xx fraction of /queries.json answers
    slo_availability_objective: float = 0.999
    # shed objective: fraction of arrivals NOT rejected by admission control
    slo_shed_objective: float = 0.99
    # -- version-keyed result cache (registry/result_cache.py) -------------
    # repeat queries answer from an LRU keyed (model_version, canonical
    # query bytes) BEFORE micro-batch admission — and even while the
    # dispatch breaker is open. 0 disables. Bypassed while a rollout is
    # active (bake gates need dispatched traffic; a canary answer is never
    # cached, so it can never be served from a stale lane).
    result_cache_size: int = 1024
    # staleness bound for serving components that read live state outside
    # the immutable model artifact (disabled-items files, constraint
    # entities); the model itself can't go stale under a version key
    result_cache_ttl_s: float = 10.0
    # -- fleet coordination (docs/fleet.md) --------------------------------
    # poll the registry's state_generation() on this cadence and adopt
    # stage/promote/rollback/stable-pin changes made by OTHER processes
    # (fleet replicas, the CLI, another replica's bake gate); 0 disables.
    # Requires a registry_dir.
    registry_sync_interval_s: float = 0.0
    # graceful drain (SIGTERM / supervised restart): how long to wait for
    # queued + in-flight queries to answer after the listener closes
    drain_grace_s: float = 15.0
    # -- profiling plane (docs/observability.md §Profiling plane) ----------
    # content-addressed profile bundle store (lazy-created on first
    # capture; newest-N GC) behind POST /profile/capture + `pio profile`
    profile_dir: str = "pio_obs/profiles"
    profile_max_bundles: int = 20
    # device-capture duration rails: ?ms= defaults/clamps here (the trace
    # buffers device events in memory — unbounded capture is a self-DoS)
    profile_default_ms: int = 500
    profile_max_ms: int = 10_000
    # always-on host stack sampler (GET /profile/stacks, pio top
    # --hotspots); <= 0 disables sampling (instruments still registered)
    sampler_period_s: float = 0.05
    # profile-on-alert: SLO-alert transitions and candidate-breaker trips
    # capture a rate-limited host-stack bundle; alert_trace_ms > 0 adds a
    # short device trace to it (off by default: a wedged device is often
    # WHY the alert fired, and a trace capture would then hang too)
    profile_on_alert: bool = True
    profile_alert_min_interval_s: float = 60.0
    profile_alert_trace_ms: int = 0
    # -- bandit exploration lanes (docs/bandit.md) -------------------------
    # policy steering the candidate traffic fraction while a rollout is
    # live: "epsilon" | "thompson"; None keeps the plain PR-4 bake gate.
    # With a policy set, the bandit owns the promote/retire decision (the
    # bake gate keeps its error/latency/divergence veto) and the plan
    # fraction follows the reward posterior every bake tick.
    bandit_policy: str | None = None
    bandit_epsilon: float = 0.1  # explore share (and cold-start fraction)
    bandit_min_pulls: int = 20  # per-arm evidence floor before deciding
    bandit_promote_threshold: float = 0.95  # P(candidate better) to promote
    bandit_retire_threshold: float = 0.05  # ... to retire the candidate
    bandit_min_fraction: float = 0.05
    bandit_max_fraction: float = 0.9
    # reward source: feedback events tailed from the event store and
    # matched to impressions by the trace id echoed into properties
    bandit_app_name: str | None = None  # app whose events carry rewards
    bandit_channel_name: str | None = None
    bandit_reward_events: tuple[str, ...] = ("reward",)
    bandit_trace_property: str = "traceId"
    bandit_reward_property: str = "reward"
    bandit_impression_capacity: int = 65536
    bandit_seed: int = 0

    def ssl_context(self):
        from predictionio_tpu.utils.tls import server_ssl_context

        return server_ssl_context(self.ssl_certfile, self.ssl_keyfile)


# Precompiled encoders, split by contract (the hot respond path must not
# pay for canonicalization it doesn't need):
#  - _fast_dumps: compact, insertion-ordered — response serialization.
#    json.dumps re-parses its kwargs into a fresh encoder per call; a
#    prebuilt JSONEncoder skips that per-request setup.
#  - _CANONICAL: sort_keys — ONLY for paths that need order-independent
#    bytes (shadow divergence comparison, result-cache keys).
# No default= on _FAST: a non-JSON-serializable value in a response body
# (a numpy scalar leaking from an engine) must raise like web.json_response
# always did, not silently reach clients as a string.
_FAST = json.JSONEncoder(separators=(",", ":"))
_fast_dumps = _FAST.encode
_CANONICAL = json.JSONEncoder(
    sort_keys=True, separators=(",", ":"), default=str
)


def _canonical_json(value: Any) -> str:
    """Order-independent JSON for shadow divergence comparison."""
    try:
        return _CANONICAL.encode(value)
    except (TypeError, ValueError):
        return repr(value)


def _canonical_query_bytes(payload: Any) -> bytes:
    """The result-cache key: canonical bytes of the raw query payload, so
    ``{"user": "u1", "num": 10}`` and ``{"num": 10, "user": "u1"}`` share
    one entry."""
    return _CANONICAL.encode(payload).encode()


def _swallow_result(fut) -> None:
    """Done-callback for executor futures the watchdog may abandon: retrieve
    the late exception so the loop never logs 'exception was never
    retrieved' for a batch that was already failed and answered."""
    if not fut.cancelled():
        fut.exception()


@dataclasses.dataclass
class _QItem:
    """One queued query: its payload, the caller's future, the request
    deadline, the ingress trace id (the contextvar does NOT survive the
    hop onto the dispatch thread — it rides here instead), the enqueue
    time (queue-wait accounting), and the mutable ``phases`` channel the
    handler shares with the batcher so per-request waterfall timestamps
    (``t_collect``/``t_done``) flow back without changing ``submit``'s
    return contract."""

    payload: Any
    fut: asyncio.Future
    deadline: Deadline
    trace_id: str | None
    t_submit: float
    phases: dict[str, float] = dataclasses.field(default_factory=dict)
    # canonical query bytes when this answer is result-cacheable (miss on
    # a quiesced stable lane); the batcher inserts the encoded body under
    # (answered version, key) once the batch resolves
    cache_key: bytes | None = None


class _MicroBatcher:
    """Coalesces concurrent /queries.json requests into batched predicts.

    Requests enqueue (payload, future) pairs; a single dispatcher pulls
    everything pending (up to ``max_batch``) and hands the batch to a
    dedicated worker thread, which runs the full decode -> supplement ->
    predict_batch -> serve pipeline off the event loop. Batching is
    *adaptive*: while the worker is busy with batch n, new arrivals
    accumulate and become batch n+1 — a solo request dispatches immediately
    (no timer penalty), a concurrent burst converges to one device call per
    batch. An optional flush window can be configured but is 0 by default.
    """

    def __init__(
        self,
        server: "QueryServer",
        max_batch: int,
        window_s: float,
        max_inflight: int = 4,
        high_water: int = 0,
        shed_retry_after_s: float = 1.0,
    ):
        import concurrent.futures

        self._server = server
        self.max_batch = max(1, max_batch)
        self.window_s = max(0.0, window_s)
        self.high_water = max(0, high_water)
        self.shed_retry_after_s = shed_retry_after_s
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._closed = False
        self._max_fetch_workers = max(1, max_inflight)
        # dispatch runs on one thread (decode + device enqueue, fast);
        # fetches block on the transport and overlap on their own threads
        self._dispatch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-dispatch"
        )
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._max_fetch_workers, thread_name_prefix="pio-fetch"
        )
        self._inflight = asyncio.Semaphore(max(1, max_inflight))
        self._finish_tasks: set[asyncio.Task] = set()
        self._cancelled_tasks: list[asyncio.Task] = []
        self.batches_dispatched = 0
        self.queries_dispatched = 0
        self.watchdog_trips = 0  # batches failed for blowing their deadline
        self.shed_count = 0  # requests rejected by admission control

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    async def submit(
        self,
        payload: Any,
        deadline: Deadline | None = None,
        phases: dict[str, float] | None = None,
        t_submit: float | None = None,
        cache_key: bytes | None = None,
    ) -> Any:
        """Enqueue one query payload; returns the encoded result body or
        raises the per-query error. Fails fast when the server is shutting
        down (never restarts the collect loop against shut-down pools) and
        sheds with ``LoadShedError`` when the queue is over high water.
        ``phases`` (optional) is filled with waterfall timestamps
        (``t_collect``, ``t_done``) as the query moves through the
        pipeline; ``t_submit`` lets the caller anchor the queue-wait phase
        at its own last measured boundary so adjacent phases tile."""
        if self._closed:
            raise ShuttingDownError()
        if self.high_water and self._queue.qsize() >= self.high_water:
            self.shed_count += 1
            self._server._m_shed.inc()
            raise LoadShedError(
                f"admission queue over high water "
                f"({self._queue.qsize()}/{self.high_water})",
                self.shed_retry_after_s,
            )
        if deadline is None:
            deadline = Deadline.never()
        fut = asyncio.get_running_loop().create_future()
        self._queue.put_nowait(
            _QItem(
                payload,
                fut,
                deadline,
                current_trace_id(),
                t_submit if t_submit is not None else time.perf_counter(),
                phases if phases is not None else {},
                cache_key,
            )
        )
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())
        return await fut

    @staticmethod
    def _fail_batch(batch: list[_QItem], exc: BaseException) -> None:
        for item in batch:
            if not item.fut.done():
                item.fut.set_exception(exc)

    def _dispatch_combined(self, items: list[_QItem]):
        """Idle fast path: dispatch AND finalize in ONE executor hop.

        The dispatch->fetch pipeline exists to overlap batch n's transport
        with batch n+1's dispatch — but a solo request on an idle server
        has nothing to overlap with, and pays two thread wakes + two
        watchdog waits for it. When the collect loop sees a batch of one
        with nothing queued and nothing in flight, the whole
        decode->dispatch->fetch->serve chain runs inside the single
        dispatch-pool call; the returned finalize is already resolved
        (``resolved`` attribute), so ``_finish`` skips the fetch executor
        entirely. Arrivals during the combined call simply form the next
        batch — exactly what adaptive batching does while a dispatch is
        busy."""
        fin = self._server._dispatch_query_batch(items)
        results = fin()

        def resolved():
            return results

        resolved.resolved = True
        resolved.timings = getattr(fin, "timings", None)
        return resolved

    def _replace_dispatch_pool(self) -> None:
        """Abandon a dispatch thread stuck past its batch's deadline: the
        single dispatch thread is the serialization point for ALL traffic,
        so a wedged device call head-of-line-blocks every later batch
        unless we walk away from it. The old executor is shut down without
        cancelling the running call (it cannot be interrupted); its thread
        finishes (or hangs) in the background while a fresh pool serves
        new batches."""
        import concurrent.futures

        old = self._dispatch_pool
        self._dispatch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-dispatch"
        )
        old.shutdown(wait=False)

    def _replace_fetch_pool(self) -> None:
        """Same walk-away for a finalize stuck on the transport. Other
        in-flight finalizes on the old pool run to completion there."""
        import concurrent.futures

        old = self._fetch_pool
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._max_fetch_workers, thread_name_prefix="pio-fetch"
        )
        old.shutdown(wait=False)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            item = await self._queue.get()
            batch = [item]
            try:
                if self.window_s > 0:
                    await asyncio.sleep(self.window_s)
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self._queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                await self._inflight.acquire()  # bound batches in flight
            except asyncio.CancelledError:
                # shutdown while holding a collected-but-undispatched batch:
                # its clients must get a response, not an eternal await
                self._fail_batch(batch, ShuttingDownError())
                raise
            # requests that expired while queued are failed here, not
            # dispatched: device work for an answer nobody is waiting on
            # would only deepen an overload
            collect_t = time.perf_counter()
            live = []
            for item in batch:
                if item.fut.done():  # client gone / cancelled
                    # its probe slot (if it held one) can never be recorded
                    self._server.dispatch_breaker.release_probe()
                    continue
                if item.deadline.expired:
                    item.fut.set_exception(
                        DeadlineExceeded("query expired in admission queue")
                    )
                else:
                    live.append(item)
                    queue_wait_s = collect_t - item.t_submit
                    item.phases["t_collect"] = collect_t
                    self._server._m_queue_wait.observe(queue_wait_s)
                    self._server.waterfall.observe(
                        PHASE_QUEUE_WAIT, queue_wait_s, item.trace_id
                    )
            if not live:
                self._inflight.release()
                continue
            batch = live
            batch_deadline = Deadline.min_of([it.deadline for it in batch])
            # idle fast path: a batch of ONE with nothing queued behind it
            # and no finalize in flight has nothing to pipeline against —
            # run dispatch AND finalize in one executor hop (see
            # _dispatch_combined); the dispatch watchdog below still bounds
            # the whole combined call. Any larger batch means the server is
            # under load, where occupying the dispatch thread through the
            # fetch would serialize the pipeline it exists to overlap.
            combined = (
                len(batch) == 1
                and self._queue.empty()
                and not self._finish_tasks
            )
            # dispatch under a watchdog. NOT wait_for(): cancelling an
            # executor future whose fn is already running blocks until the
            # fn returns — the exact hang the watchdog exists to escape.
            # asyncio.wait() times out without cancelling; the stuck call
            # is then abandoned and its pool replaced.
            dispatch_t0 = time.perf_counter()
            try:
                # the batch list itself is the handoff — the dispatch
                # thread reads payload/trace_id straight off the queued
                # items (no per-batch tuple-list materialization)
                exec_fut = loop.run_in_executor(
                    self._dispatch_pool,
                    self._dispatch_combined
                    if combined
                    else self._server._dispatch_query_batch,
                    batch,
                )
                exec_fut.add_done_callback(_swallow_result)
                done, pending = await asyncio.wait(
                    [exec_fut], timeout=batch_deadline.remaining()
                )
            except asyncio.CancelledError:
                self._inflight.release()
                # shutdown mid-dispatch: this batch's clients must get a
                # response too (close()'s drain only covers queued items)
                self._fail_batch(batch, ShuttingDownError())
                raise  # close() must actually terminate the collect loop
            if pending:
                # watchdog trip: fail THIS batch, walk away from the stuck
                # dispatch thread, keep serving everyone else
                self._inflight.release()
                self.watchdog_trips += 1
                self._server._m_watchdog.inc()
                self._replace_dispatch_pool()
                self._server.dispatch_breaker.record_failure()
                self._fail_batch(
                    batch,
                    DeadlineExceeded("micro-batch dispatch: deadline exceeded"),
                )
                continue
            dispatch_s = time.perf_counter() - dispatch_t0
            try:
                finalize = exec_fut.result()
            except BaseException as exc:
                self._inflight.release()
                self._server.dispatch_breaker.record_failure()
                for item in batch:
                    if not item.fut.done():
                        item.fut.set_exception(exc)
                continue
            if getattr(finalize, "resolved", False):
                # combined fast path: the measured dispatch window swallowed
                # device compute + serve; carve them back out so _finish's
                # device/serve observations keep the waterfall tiling
                t = getattr(finalize, "timings", None) or {}
                dispatch_s = max(
                    0.0,
                    dispatch_s
                    - t.get("device_s", 0.0)
                    - t.get("serve_s", 0.0),
                )
            self._server._m_dispatch.observe(dispatch_s)
            # batch-scoped waterfall phases: every rider waits out the whole
            # batch, so each query is accounted the batch's duration
            assembly_s = max(0.0, dispatch_t0 - collect_t)
            for item in batch:
                self._server.waterfall.observe(
                    PHASE_BATCH_ASSEMBLY, assembly_s, item.trace_id
                )
                self._server.waterfall.observe(
                    PHASE_DISPATCH, dispatch_s, item.trace_id
                )
            self.batches_dispatched += 1
            self.queries_dispatched += len(batch)
            # finish asynchronously: the collect loop immediately forms and
            # dispatches the next batch while this one's fetch is in flight
            task = asyncio.ensure_future(
                self._finish(
                    batch,
                    finalize,
                    batch_deadline,
                    dispatch_s,
                    dispatch_t0 + dispatch_s,
                )
            )
            self._finish_tasks.add(task)
            task.add_done_callback(self._finish_tasks.discard)

    async def _finish(
        self,
        batch: list[_QItem],
        finalize,
        deadline: Deadline,
        dispatch_s: float = 0.0,
        dispatch_end: float = 0.0,
    ) -> None:
        loop = asyncio.get_running_loop()
        fetch_t0 = time.perf_counter()
        if getattr(finalize, "resolved", False):
            # combined fast path (_dispatch_combined): the dispatch call
            # already ran finalize on the dispatch thread under the dispatch
            # watchdog — results are in hand, no fetch hop. The device
            # transport DID block inside that call (the finalize's device_s
            # window), so it still counts as stall time: an idle-but-serving
            # instance, where every solo request takes this path, must not
            # read as zero-stall
            results = finalize()
            fetch_s = time.perf_counter() - fetch_t0
            self._server._m_fetch.observe(fetch_s)
            device_s = (getattr(finalize, "timings", None) or {}).get(
                "device_s", 0.0
            )
            if device_s > 0.0:
                self._server._m_stall.inc(device_s, where="micro-batch-fetch")
            self._server.dispatch_breaker.record_success()
            self._inflight.release()
        else:
            exec_fut = loop.run_in_executor(self._fetch_pool, finalize)
            exec_fut.add_done_callback(_swallow_result)
            try:
                done, pending = await asyncio.wait(
                    [exec_fut], timeout=deadline.remaining()
                )
            except asyncio.CancelledError:
                self._inflight.release()
                # shutdown: resolve the batch's futures (handlers awaiting
                # them would otherwise hang for aiohttp's whole shutdown
                # timeout)
                self._fail_batch(batch, ShuttingDownError())
                raise
            if pending:
                # fetch watchdog: same walk-away as dispatch (see _run);
                # other finalizes in flight on the old pool still run to
                # completion
                self._inflight.release()
                self.watchdog_trips += 1
                self._server._m_watchdog.inc()
                self._replace_fetch_pool()
                self._server.dispatch_breaker.record_failure()
                self._fail_batch(
                    batch,
                    DeadlineExceeded("micro-batch fetch: deadline exceeded"),
                )
                return
            fetch_s = time.perf_counter() - fetch_t0
            self._server._m_fetch.observe(fetch_s)
            # the fetch phase is where the host blocks on the device
            # transport: account it as stall time (see obs/jaxprof.py)
            self._server._m_stall.inc(fetch_s, where="micro-batch-fetch")
            try:
                results = exec_fut.result()
            except BaseException as exc:
                # a finalize that raised wholesale is a dispatch-path
                # failure (per-query errors are isolated inside finalize and
                # arrive as entries in the results) — it must count against
                # the breaker exactly like a failed dispatch, not close a
                # half-open circuit
                results = [(exc, "")] * len(batch)
                self._server.dispatch_breaker.record_failure()
            else:
                self._server.dispatch_breaker.record_success()
            finally:
                self._inflight.release()
        done_t = time.perf_counter()
        # waterfall decomposition of the dispatch-end -> results-distributed
        # window: device compute and serve are measured inside finalize (it
        # publishes them via its `timings` attribute); everything else in
        # the window — executor hop, transport readback, result unpack — is
        # the fetch residual
        timings = getattr(finalize, "timings", None) or {}
        device_s = max(0.0, timings.get("device_s", 0.0))
        serve_s = max(0.0, timings.get("serve_s", 0.0))
        window_s = (done_t - dispatch_end) if dispatch_end else fetch_s
        fetch_resid_s = max(0.0, window_s - device_s - serve_s)
        wf = self._server.waterfall
        for item, (out, version) in zip(batch, results):
            wf.observe(PHASE_DEVICE_COMPUTE, device_s, item.trace_id)
            wf.observe(PHASE_FETCH, fetch_resid_s, item.trace_id)
            wf.observe(PHASE_SERVE, serve_s, item.trace_id)
            if item.cache_key is not None and not isinstance(out, BaseException):
                self._server._cache_store(version, item.cache_key, out)
            item.phases["t_done"] = done_t
            queue_s = max(
                0.0, item.phases.get("t_collect", item.t_submit) - item.t_submit
            )
            # one `batch` span per query, carrying the full phase waterfall
            # AND the model version that answered — the hop between the
            # ingress span and any storage spans the engine's serving
            # components recorded
            self._server.tracer.record_span(
                "query.batch",
                kind="batch",
                duration_s=done_t - item.t_submit,
                trace_id=item.trace_id,
                status=type(out).__name__ if isinstance(out, BaseException) else "ok",
                batch_size=len(batch),
                version=version,
                queue_ms=round(queue_s * 1000, 3),
                dispatch_ms=round(dispatch_s * 1000, 3),
                fetch_ms=round(fetch_s * 1000, 3),
                **phase_tags_ms(
                    device_compute=device_s,
                    serve=serve_s,
                    fetch_residual=fetch_resid_s,
                ),
            )
            if item.fut.done():  # client gone / cancelled
                continue
            if isinstance(out, BaseException):
                item.fut.set_exception(out)
            else:
                item.fut.set_result(out)

    def close(self) -> None:
        self._closed = True  # new submits fail fast from here on
        if self._task is not None:
            self._task.cancel()
            self._cancelled_tasks.append(self._task)
            self._task = None
        for task in list(self._finish_tasks):
            task.cancel()
            self._cancelled_tasks.append(task)
        # fail everything still queued: enqueued-but-never-collected items
        # have handlers awaiting their futures (collected/dispatched batches
        # are resolved by the _run/_finish cancellation paths)
        exc = ShuttingDownError()
        while True:
            try:
                item = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if not item.fut.done():
                item.fut.set_exception(exc)
        self._dispatch_pool.shutdown(wait=False, cancel_futures=True)
        self._fetch_pool.shutdown(wait=False, cancel_futures=True)

    async def wait_closed(self) -> None:
        """Drain the cancellations issued by ``close()`` so shutdown leaves
        zero pending asyncio tasks behind."""
        tasks = [t for t in self._cancelled_tasks if not t.done()]
        self._cancelled_tasks.clear()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)


class QueryServer:
    def __init__(
        self,
        engine: Engine,
        engine_params: EngineParams,
        models: list[Any],
        manifest: EngineManifest,
        instance_id: str,
        storage: Storage | None = None,
        config: ServerConfig | None = None,
        plugin_context=None,
        registry_store: ArtifactStore | None = None,
        model_version: str | None = None,
    ):
        from predictionio_tpu.workflow.server_plugins import (
            EngineServerPluginContext,
        )

        self.engine = engine
        self.engine_params = engine_params
        self.manifest = manifest
        self.instance_id = instance_id
        self.storage = storage or Storage.instance()
        self.config = config or ServerConfig()
        self.plugin_context = plugin_context or EngineServerPluginContext()
        self.registry_store = registry_store or (
            ArtifactStore(self.config.registry_dir)
            if self.config.registry_dir
            else None
        )
        _, _, algorithms, serving = engine.make_components(engine_params)
        # (algorithms, serving, models, version) live in ONE Lane tuple
        # swapped atomically: the dispatch thread snapshots it in a single
        # attribute read, so a concurrent /reload or promote can never pair
        # new algorithms with old models (attribute-by-attribute assignment
        # allowed exactly that interleave)
        self._active: Lane = Lane(
            algorithms,
            serving,
            models,
            model_version or instance_id,
            instance_id,
            engine_params,
        )
        # progressive rollout: an optional candidate Lane next to stable,
        # with the routing plan snapshotted separately (an in-flight batch
        # keeps whatever lanes it read — same contract as /reload)
        self._candidate: Lane | None = None
        self._plan: RolloutPlan = PLAN_OFF
        # serializes lane swaps across the event loop (promote endpoint,
        # controller tick) and dispatch threads (breaker-trip rollback)
        self._rollout_mutex = threading.Lock()
        self._rollout_task: asyncio.Task | None = None
        # fleet coordination: the registry state generation this process
        # last reconciled against (None = never; first tick reconciles,
        # which is exactly right after a crash-restart mid-bake)
        self._registry_sync_task: asyncio.Task | None = None
        self._seen_state_gen: int | None = None
        # graceful drain: listener closed, in-flight answered, then exit
        self._draining = False
        self._inflight_requests = 0
        self._drain_task: asyncio.Task | None = None
        # rollout generation: bumped on every stage/promote/rollback so
        # in-flight shadow work (queued behind a slow candidate) can tell
        # it belongs to a PREVIOUS rollout and must not feed the breaker
        # or counters of the current one
        self._rollout_gen = 0
        self._shadow_lock = threading.Lock()
        self._shadow_pending = 0
        self.start_time = _dt.datetime.now(tz=UTC)
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0
        # -- observability (docs/observability.md) --------------------------
        self.metrics = MetricsRegistry()
        self.tracer: Tracer = get_tracer()
        m = self.metrics
        self._m_requests = m.counter(
            "pio_requests_total",
            "HTTP requests served, by route and status",
            labelnames=("endpoint", "status"),
        )
        # ONE latency histogram backs both the legacy `/` status page and
        # /metrics — two independent ladders reported different p95s for
        # the same traffic and sent operators chasing phantom regressions
        self._m_latency = m.histogram(
            "pio_request_seconds",
            "HTTP request wall time, by route",
            labelnames=("endpoint",),
        )
        self._m_queue_wait = m.histogram(
            "pio_queue_wait_seconds",
            "time queries spend in the micro-batch admission queue",
        )
        self._m_dispatch = m.histogram(
            "pio_dispatch_seconds",
            "micro-batch dispatch phase (decode + device enqueue) wall time",
        )
        self._m_fetch = m.histogram(
            "pio_fetch_seconds",
            "micro-batch fetch phase (device->host transport + serve) wall time",
        )
        self._m_stall = m.counter(
            "pio_device_stall_seconds_total",
            "cumulative seconds spent blocked on device->host synchronization",
            labelnames=("where",),
        )
        self._m_shed = m.counter(
            "pio_load_shed_total",
            "requests rejected by admission control (503 + Retry-After)",
        )
        self._m_deadline = m.counter(
            "pio_deadline_exceeded_total",
            "requests failed for blowing their deadline (queued or in flight)",
        )
        self._m_watchdog = m.counter(
            "pio_watchdog_trips_total",
            "batches abandoned because a device call blew its deadline",
        )
        self._m_breaker_rejected = m.counter(
            "pio_breaker_rejections_total",
            "requests shed at the door because the dispatch circuit was open",
        )
        self._breaker_instruments = BreakerInstruments(m)
        # per-request latency attribution: every query accounted into the
        # phase waterfall (pio_phase_seconds{phase=...}) with trace-id
        # exemplars — see obs/waterfall.py for the phase boundaries
        self.waterfall = PhaseWaterfall(m)
        # version-keyed result cache (registry/result_cache.py): repeat
        # queries on a quiesced stable lane answer BEFORE batch admission.
        # The pio_cache_* counters mirror the cache's own monotonic stats
        # at scrape time (same set_total pattern as the batcher counters).
        self._result_cache: ResultCache | None = (
            ResultCache(
                self.config.result_cache_size, self.config.result_cache_ttl_s
            )
            if self.config.result_cache_size > 0
            else None
        )
        self._m_cache_hits = m.counter(
            "pio_cache_hits_total",
            "queries answered from the version-keyed result cache "
            "(never entered the micro-batch queue)",
        )
        self._m_cache_misses = m.counter(
            "pio_cache_misses_total",
            "cacheable queries that missed and went through dispatch",
        )
        self._m_cache_evictions = m.counter(
            "pio_cache_evictions_total",
            "result-cache entries dropped by LRU pressure or TTL expiry",
        )
        self._m_cache_invalidations = m.counter(
            "pio_cache_invalidations_total",
            "result-cache entries flushed by model swap/promote/rollback/"
            "stage/reload",
        )
        if self._result_cache is not None:
            m.register_collector(self._collect_cache)
        # declarative SLOs evaluated as multi-window burn rates from the
        # instruments above (obs/slo.py): /slo + pio_slo_* gauges
        self.slo = SLOEngine(m)
        _queries = "/queries.json"
        self.slo.add(
            "latency",
            f"{_queries} answered within "
            f"{self.config.slo_latency_threshold_s * 1000:g} ms",
            self.config.slo_latency_objective,
            histogram_threshold_source(
                self._m_latency,
                self.config.slo_latency_threshold_s,
                endpoint=_queries,
            ),
        )
        self.slo.add(
            "availability",
            f"{_queries} answered without a 5xx",
            self.config.slo_availability_objective,
            counter_ratio_source(
                self._m_requests,
                bad=lambda l: l.get("status", "").startswith("5"),
                match=lambda l: l.get("endpoint") == _queries,
            ),
        )
        self.slo.add(
            "shed",
            f"{_queries} arrivals not rejected by admission control",
            self.config.slo_shed_objective,
            paired_counter_source(
                counter_ratio_source(
                    self._m_requests,
                    bad=lambda l: False,
                    match=lambda l: l.get("endpoint") == _queries,
                ),
                self._m_shed,
            ),
        )
        # the pio_ann_* family (docs/ann.md): registered eagerly so the
        # family exists from process start; lanes loaded from the registry
        # bind their attached AnnServing to it in _warmup_components. The
        # collector reconciles the version-labeled index gauges against
        # the LIVE lanes each scrape — a reload must retire the old
        # version's series, not leave it rendering as pinned forever
        self.ann_instruments = AnnInstruments(m)
        m.register_collector(self._collect_ann_indexes)
        # jit cache misses / XLA compile events become first-class metrics;
        # sampled at scrape time via the registry collector hook
        self.compile_watcher = CompileWatcher(m)
        m.register_collector(self.compile_watcher.sample)
        m.register_collector(self._breaker_instruments.collect)
        m.register_collector(self.slo.collect)
        # registry lease-mutex counters (registry/lease.py): every server
        # that can stage/promote through the shared-storage registry
        # exports its acquire/steal/fencing-loss tallies
        from predictionio_tpu.registry.lease import register_lease_metrics

        register_lease_metrics(m)
        self._runner: web.AppRunner | None = None
        self._stop_event = asyncio.Event()
        # strong refs to fire-and-forget tasks (the loop keeps only weak ones)
        self._bg_tasks: set[asyncio.Task] = set()
        # ONE shared session with a total timeout for all background HTTP
        # (feedback + remote log): per-call bare ClientSessions with no
        # timeout accumulated hung tasks forever against a stalled collector
        self._http_session = None
        # consecutive watchdog trips (device calls blowing their deadline)
        # open this breaker; while open /queries.json sheds instantly with
        # 503 + Retry-After instead of feeding more work to a wedged device
        self.dispatch_breaker = self._breaker_instruments.watch(
            CircuitBreaker(
                name="dispatch",
                failure_threshold=self.config.breaker_threshold,
                recovery_timeout_s=self.config.breaker_recovery_s,
            )
        )
        # candidate-lane breaker: consecutive candidate predict failures
        # force an instant rollback (no bake-window wait) via the chained
        # trip listener; the obs instruments see its transitions too
        self.candidate_breaker = self._breaker_instruments.watch(
            CircuitBreaker(
                name="candidate",
                failure_threshold=self.config.candidate_breaker_threshold,
                recovery_timeout_s=self.config.breaker_recovery_s,
            )
        )
        self.candidate_breaker.chain_listener(self._on_candidate_transition)
        self._rollout_instruments = RolloutInstruments(m)
        self.rollout_controller = RolloutController(
            self._rollout_instruments,
            PromotionCriteria(
                bake_window_s=self.config.bake_window_s,
                min_requests=self.config.bake_min_requests,
                max_error_ratio=self.config.max_error_ratio,
                max_p95_ratio=self.config.max_p95_ratio,
                max_divergence_rate=self.config.max_divergence_rate,
                auto_promote=self.config.auto_promote,
            ),
        )
        # -- bandit exploration lanes (docs/bandit.md): the pio_bandit_*
        # family registers eagerly (exists at zero with no policy, same
        # discipline as AnnInstruments); the loop itself only exists when
        # a policy is configured. It rides the rollout machinery: arms are
        # the stable/candidate lanes, the policy's actuator is the canary
        # fraction, and promote/retire route through the existing
        # transitions — so a losing arm retires with zero client 5xx.
        self.bandit_instruments = BanditInstruments(m)
        self.bandit: BanditLoop | None = (
            BanditLoop(
                self.config.bandit_policy,
                epsilon=self.config.bandit_epsilon,
                criteria=BanditCriteria(
                    min_pulls=float(self.config.bandit_min_pulls),
                    promote_threshold=self.config.bandit_promote_threshold,
                    retire_threshold=self.config.bandit_retire_threshold,
                    min_fraction=self.config.bandit_min_fraction,
                    max_fraction=self.config.bandit_max_fraction,
                ),
                instruments=self.bandit_instruments,
                store=self.registry_store,
                engine_id=self.manifest.engine_id,
                impression_capacity=self.config.bandit_impression_capacity,
                seed=self.config.bandit_seed,
            )
            if self.config.bandit_policy
            else None
        )
        self._reload_lock = asyncio.Lock()
        self._batcher = _MicroBatcher(
            self,
            max_batch=self.config.max_batch_size,
            window_s=self.config.batch_window_ms / 1000.0,
            high_water=self.config.queue_high_water,
            shed_retry_after_s=self.config.shed_retry_after_s,
        )
        # scrape-time gauges mirroring live batcher state (hot path pays 0)
        m.gauge(
            "pio_queue_depth", "queries waiting in the micro-batch queue"
        ).set_function(lambda: self._batcher.queue_depth)
        m.gauge(
            "pio_queue_high_water",
            "admission-control shed threshold (0 = unbounded)",
        ).set(self.config.queue_high_water)
        import concurrent.futures

        self._sniffer_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-sniffer"
        )
        # shadow scoring runs off the serving path entirely: the candidate
        # is scored on this thread, its answer discarded, divergence
        # recorded — a slow or crashing candidate cannot touch a response
        self._shadow_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-shadow"
        )
        # -- profiling plane (docs/observability.md §Profiling plane) -------
        # always-on host stack sampler + single-flight device capture. Both
        # register their pio_profile_* instruments eagerly here so the
        # family exists from process start (the metrics contract test
        # resolves every documented metric against a fresh server).
        self.sampler = HostSampler(
            period_s=self.config.sampler_period_s
            if self.config.sampler_period_s > 0
            else 0.05,
            metrics=m,
        )
        self.profiler = ProfileSession(
            ProfileStore(
                self.config.profile_dir, self.config.profile_max_bundles
            ),
            default_ms=self.config.profile_default_ms,
            max_ms=self.config.profile_max_ms,
            alert_min_interval_s=self.config.profile_alert_min_interval_s,
            alert_trace_ms=self.config.profile_alert_trace_ms,
            context_fn=self._profile_context,
            metrics=m,
        )
        # SLO alert-transition tracker for profile-on-alert (the rollout
        # heartbeat checks it): a long burn must capture ONCE per
        # transition, not once per tick
        self._slo_alerting: dict[str, bool] = {}

    def _profile_context(self) -> dict[str, Any]:
        """Manifest enrichment for every profile bundle: which engine and
        model were serving, at which registry generation — the trace
        viewer can't answer that, the manifest must."""
        generation = None
        if self.registry_store is not None:
            try:
                generation = self.registry_store.state_generation(
                    self.manifest.engine_id
                )
            except Exception:  # noqa: BLE001 - enrichment, not evidence
                generation = None
        return {
            "engine": self.manifest.engine_id,
            "engineVersion": self.manifest.version,
            "modelVersion": self._active.version,
            "instanceId": self.instance_id,
            "registryGeneration": generation,
        }

    def _profile_parts(self) -> dict[str, Any]:
        """Host-side evidence attached to every profile bundle: the phase
        waterfall at capture time and the sampler's folded stacks."""
        return {
            "waterfall": self.waterfall.snapshot(),
            "stacks": self.sampler.snapshot(),
        }

    def _capture_profile(self, ms: int | None, trigger: str) -> str:
        """Blocking capture body (trace sleep + bundle file writes): runs
        on an executor thread, never on the event loop."""
        return self.profiler.capture(
            ms=ms, trigger=trigger, parts=self._profile_parts()
        )

    # ---------------------------------------------------------------- routes
    async def handle_queries(self, request: web.Request) -> web.Response:
        """Trace + metrics envelope around the query path: accept or mint
        the request's trace id (echoed in the response), record the
        ingress span, and count/observe every status — including the
        shed/deadline 503s the resilience layer used to decide silently."""
        trace_id = request.headers.get(TRACE_HEADER) or mint_trace_id()
        token = set_trace_id(trace_id)
        status = 500
        t0 = time.perf_counter()
        # drain accounting: the SIGTERM drain path waits for this count to
        # reach zero before the process exits, so a supervised restart
        # answers everything it already accepted
        self._inflight_requests += 1
        # per-request waterfall channel: the inner handler and the batcher
        # fill it with phase timestamps; the ingress span carries the
        # handler-side phases as tags
        phases: dict[str, float] = {"t_start": t0}
        try:
            with self.tracer.span(
                "http.query", kind="ingress", endpoint="/queries.json"
            ) as sp:
                resp = await self._handle_queries_inner(request, phases)
                status = resp.status
                sp.tags["status"] = status
                if phases.get("t_done") is not None:
                    phases["respond_s"] = time.perf_counter() - phases["t_done"]
                sp.tags.update(
                    phase_tags_ms(
                        ingress_parse=phases.get("parse_s"),
                        respond=phases.get("respond_s"),
                    )
                )
        finally:
            reset_trace_id(token)
            self._inflight_requests -= 1
            # ONE end timestamp anchors both the e2e histogram and the
            # respond phase, so the waterfall tiles the same wall clock the
            # latency histogram reports (the reconciliation contract)
            t_end = time.perf_counter()
            self._m_requests.inc(endpoint="/queries.json", status=str(status))
            self._m_latency.observe(t_end - t0, endpoint="/queries.json")
            t_done = phases.get("t_done")
            if t_done is not None:
                self.waterfall.observe(PHASE_RESPOND, t_end - t_done, trace_id)
        resp.headers[TRACE_HEADER] = trace_id
        return resp

    async def _handle_queries_inner(
        self, request: web.Request, phases: dict[str, float] | None = None
    ) -> web.Response:
        phases = {} if phases is None else phases
        if self.config.accesskey:
            supplied = request.query.get("accessKey") or request.headers.get(
                "Authorization", ""
            ).removeprefix("Bearer ").strip()
            if supplied != self.config.accesskey:
                return web.json_response({"message": "Invalid accessKey."}, status=401)
        t0 = time.perf_counter()
        if (
            self.config.max_payload_bytes
            and request.content_length is not None
            and request.content_length > self.config.max_payload_bytes
        ):
            return web.json_response(
                {
                    "message": (
                        f"query payload too large "
                        f"({request.content_length} > "
                        f"{self.config.max_payload_bytes} bytes)"
                    )
                },
                status=413,
            )
        try:
            payload = await request.json()
        except Exception as exc:
            return web.json_response({"message": str(exc)}, status=400)
        # ingress parse complete (auth + size check + JSON decode) — the
        # first waterfall phase. The same timestamp anchors the cache
        # phase so the two tile exactly.
        t_parse_end = time.perf_counter()
        parse_s = t_parse_end - phases.get("t_start", t0)
        phases["parse_s"] = parse_s
        self.waterfall.observe(PHASE_INGRESS_PARSE, parse_s, current_trace_id())
        # ---- version-keyed result cache, consulted BEFORE admission ----
        # (and before the breaker check: a wedged device must not block
        # answers the cache already holds). A hit's waterfall is
        # parse -> cache -> respond; a miss pays the lookup in the cache
        # phase and carries its canonical key so the batcher can insert
        # the answer under the version that actually served it.
        cache = self._result_cache
        cache_key: bytes | None = None
        t_anchor = t_parse_end
        if cache is not None:
            entry = None
            version = self._cache_lookup_version()
            if version is not None:
                try:
                    cache_key = _canonical_query_bytes(payload)
                except (TypeError, ValueError):
                    cache_key = None
                if cache_key is not None:
                    entry = cache.get(version, cache_key)
            t_cache_end = time.perf_counter()
            cache_s = t_cache_end - t_parse_end
            phases["cache_s"] = cache_s
            self.waterfall.observe(PHASE_CACHE, cache_s, current_trace_id())
            t_anchor = t_cache_end
            if entry is not None:
                self._m_cache_hits.inc()
                phases["t_done"] = t_cache_end
                text = entry.text
                if text is None:
                    # serialize once per entry; every later hit's respond
                    # phase is a prebuilt-string write
                    text = entry.text = _fast_dumps(entry.body)
                elapsed = time.perf_counter() - t0
                self.request_count += 1
                self.last_serving_sec = elapsed
                self.avg_serving_sec += (
                    elapsed - self.avg_serving_sec
                ) / self.request_count
                if self.config.feedback:
                    self._spawn_bg(self._send_feedback(payload, entry.body))
                return web.Response(
                    text=text, content_type="application/json"
                )
            if cache_key is not None:
                self._m_cache_misses.inc()
        try:
            # a wedged device has tripped the dispatch breaker: shed at the
            # door with a Retry-After instead of queueing doomed work
            self.dispatch_breaker.allow()
        except CircuitOpenError as exc:
            self._m_breaker_rejected.inc()
            return self._unavailable(
                "serving temporarily unavailable (dispatch circuit open)",
                exc.retry_after_s,
            )
        deadline = Deadline.after(self.config.request_timeout_s)
        try:
            # the batcher runs decode -> supplement -> predict_batch -> serve
            # on its worker thread, so the event loop never blocks on device
            # or storage work and concurrent requests coalesce into one
            # batched device call; the deadline rides along and bounds every
            # stage (queue wait, dispatch, result fetch — the breaker
            # admission above is accounted into queue_wait via the anchor)
            body = await self._batcher.submit(
                payload,
                deadline,
                phases=phases,
                t_submit=t_anchor,
                cache_key=cache_key,
            )
        except LoadShedError as exc:
            # this request died before any dispatch could record against the
            # breaker: free its half-open probe slot (no-op when closed/open)
            # or an unresolved probe would wedge the circuit half-open
            self.dispatch_breaker.release_probe()
            return self._unavailable(str(exc), exc.retry_after_s)
        except DeadlineExceeded as exc:
            self.dispatch_breaker.release_probe()
            self._m_deadline.inc()
            logger.warning("query deadline exceeded: %s", exc)
            return self._unavailable(str(exc), self.config.shed_retry_after_s)
        except ShuttingDownError as exc:
            self.dispatch_breaker.release_probe()
            return self._unavailable(str(exc), self.config.shed_retry_after_s)
        except Exception as exc:
            logger.exception("query failed")
            if self.config.log_url:
                import traceback

                tb = "".join(traceback.format_exception(exc))
                msg = f"Query:\n{payload}\n\nStack Trace:\n{tb}\n\n"
                self._spawn_bg(self._remote_log(msg))
            return web.json_response({"message": str(exc)}, status=400)
        elapsed = time.perf_counter() - t0
        self.request_count += 1
        self.last_serving_sec = elapsed
        self.avg_serving_sec += (elapsed - self.avg_serving_sec) / self.request_count
        if self.config.feedback:
            self._spawn_bg(self._send_feedback(payload, body))
        # the respond phase (results distributed -> future resumed ->
        # response serialized) is observed by the envelope in
        # handle_queries, anchored on the same end timestamp as the e2e
        # latency histogram; the precompiled compact encoder keeps it off
        # the sort_keys canonical path
        return web.json_response(body, dumps=_fast_dumps)

    def _dispatch_query_batch(self, items: list[_QItem]):
        """Dispatch-phase of one micro-batch (runs on the dispatch thread):
        decode and supplement each query, then *dispatch* every algorithm's
        device work via ``predict_batch_dispatch`` without blocking on
        results. Returns a finalize callable (run on a fetch thread) that
        blocks on the transport, serves, and encodes — so the dispatcher can
        start batch n+1 while batch n's results are in flight.

        ``items`` is the batcher's queued-item list itself (payload +
        ingress trace id read in place — zero per-batch re-packing); the
        trace id is re-installed around the per-query stages
        (decode/supplement here, serve in finalize) so spans those stages
        record — a serving component fetching user features from storage,
        say — join the request's trace across the thread hop.

        Rollout routing happens here: ONE read each of ``_active`` /
        ``_candidate`` / ``_plan`` means an in-flight batch is immune to
        /reload, promote, and rollback and always sees a consistent
        (algorithms, serving, models, version) quadruple per lane. During
        a canary, each query's sticky key routes it to stable or candidate
        *before* supplement (the lanes own separate serving components);
        candidate-lane failures never surface to users — they feed the
        candidate breaker (whose trip forces instant rollback) and the
        query is re-answered on the stable lane.

        Per-query failures are isolated: the failing slot gets its
        exception, batch mates answer normally. Finalize returns one
        ``(encoded result body or exception, model version)`` pair per
        payload; the version rides into the per-query batch span."""
        stable: Lane = self._active
        cand: Lane | None = self._candidate
        plan = self._plan
        gen = self._rollout_gen
        canary = (
            cand is not None and plan.mode == MODE_CANARY and plan.fraction > 0
        )
        shadow = cand is not None and plan.mode == MODE_SHADOW
        # bandit accounting is snapshotted with the lanes: answered queries
        # of THIS batch are pulls of THIS rollout's arms (the version check
        # inside record_impression drops any race with promote/rollback)
        bandit = (
            self.bandit
            if canary and self.bandit is not None and self.bandit.active
            else None
        )
        payloads = [it.payload for it in items]
        trace_ids = [it.trace_id for it in items]
        n = len(payloads)
        outs: list[Any] = [None] * n
        versions: list[str] = [stable.version] * n
        queries: list[Any] = [None] * n
        supplemented: list[Any] = [None] * n
        stable_idx: list[int] = []
        cand_idx: list[int] = []
        inst = self._rollout_instruments
        for i, payload in enumerate(payloads):
            token = set_trace_id(trace_ids[i])
            try:
                try:
                    q = self.engine.decode_query(payload)
                    queries[i] = q
                except Exception as exc:
                    # client error (bad payload) — no lane touched it, so
                    # no per-version accounting
                    outs[i] = exc
                    continue
                lane = stable
                if canary and (
                    choose_lane(
                        plan,
                        routing_key(payload, self.config.sticky_key_field),
                    )
                    == LANE_CANDIDATE
                ):
                    # a failing candidate supplement degrades this query to
                    # the stable answer, not to an error; the failure is
                    # paired with a request so the error-RATE gate compares
                    # like with like
                    try:
                        supplemented[i] = cand.serving.supplement(q)
                        lane = cand
                    except Exception:
                        logger.exception("candidate supplement failed")
                        if gen == self._rollout_gen:
                            inst.requests.inc(
                                version=cand.version, lane=LANE_CANDIDATE
                            )
                        self._record_candidate_failure(cand.version, gen)
                if lane is stable:
                    try:
                        supplemented[i] = stable.serving.supplement(q)
                    except Exception as exc:
                        # symmetric accounting: a stable supplement failure
                        # is a stable-lane error, not silence — otherwise a
                        # flaky shared dependency reads as candidate-only
                        # and rolls back a candidate no worse than stable
                        inst.requests.inc(
                            version=stable.version, lane=LANE_STABLE
                        )
                        inst.errors.inc(
                            version=stable.version, lane=LANE_STABLE
                        )
                        outs[i] = exc
                        continue
                    stable_idx.append(i)
                else:
                    versions[i] = cand.version
                    cand_idx.append(i)
            finally:
                reset_trace_id(token)
        dispatched: list[tuple[Lane, str, list[int], list[Any], list[Any]]] = []
        for lane, lane_name, idxs in (
            (stable, LANE_STABLE, stable_idx),
            (cand, LANE_CANDIDATE, cand_idx),
        ):
            if lane is None or not idxs:
                continue
            sup = [supplemented[i] for i in idxs]
            finalizers: list[Any] = []
            for algo, model in zip(lane.algorithms, lane.models):
                fin = None
                try:
                    fin = algo.predict_batch_dispatch(model, sup)
                except Exception:
                    logger.exception(
                        "predict_batch_dispatch failed; deferring to fetch"
                    )
                finalizers.append(fin)
            dispatched.append((lane, lane_name, idxs, sup, finalizers))

        # finalize publishes its measured sub-phases here: the fetch-thread
        # wall decomposes into device compute (blocked on device results),
        # serve (per-query serve + encode), and a transport/hop residual
        # the batcher derives (see _finish)
        timings: dict[str, float] = {"device_s": 0.0, "serve_s": 0.0}

        def finalize() -> list[tuple[Any, str]]:
            sniffed: list[tuple[Any, Any]] = []
            inst = self._rollout_instruments
            for lane, lane_name, idxs, sup, finalizers in dispatched:
                t0 = time.perf_counter()
                preds_per_algo = self._lane_predictions(lane, sup, finalizers)
                lane_predict_s = time.perf_counter() - t0
                timings["device_s"] += lane_predict_s
                inst.predict_seconds.observe(
                    lane_predict_s, version=lane.version
                )
                for row, i in enumerate(idxs):
                    token = set_trace_id(trace_ids[i])
                    t_serve = time.perf_counter()
                    # candidate accounting is generation-scoped end to end:
                    # a stale batch must not add errorless requests to the
                    # denominator of the NEW candidate's error-rate gate
                    # (its errors are already dropped by the gen guard)
                    if lane_name != LANE_CANDIDATE or gen == self._rollout_gen:
                        inst.requests.inc(version=lane.version, lane=lane_name)
                    try:
                        outs[i] = self._serve_one(
                            lane,
                            queries[i],
                            [preds[row] for preds in preds_per_algo],
                            sniffed,
                        )
                        if lane_name == LANE_CANDIDATE and gen == self._rollout_gen:
                            # same generation guard as the failure paths: a
                            # stale batch's successes must not reset the
                            # consecutive-failure count a failing successor
                            # candidate is accumulating
                            self.candidate_breaker.record_success()
                        if bandit is not None:
                            # an answered query is a pull the moment it is
                            # served; the trace id becomes matchable for
                            # feedback credit
                            bandit.record_impression(
                                trace_ids[i],
                                ARM_CANDIDATE
                                if lane_name == LANE_CANDIDATE
                                else ARM_STABLE,
                                lane.version,
                            )
                    except Exception as exc:
                        if lane_name == LANE_CANDIDATE:
                            self._record_candidate_failure(lane.version, gen)
                            outs[i], versions[i] = self._stable_retry(
                                stable, queries[i], sniffed
                            )
                            if bandit is not None and not isinstance(
                                outs[i], BaseException
                            ):
                                # re-answered on stable: that's a stable pull
                                bandit.record_impression(
                                    trace_ids[i], ARM_STABLE, stable.version
                                )
                        else:
                            inst.errors.inc(
                                version=lane.version, lane=lane_name
                            )
                            outs[i] = exc
                    finally:
                        timings["serve_s"] += time.perf_counter() - t_serve
                        reset_trace_id(token)
            if shadow:
                pairs = [
                    (queries[i], outs[i])
                    for i in stable_idx
                    if not isinstance(outs[i], BaseException)
                ]
                if pairs:
                    self._submit_shadow(cand, pairs, gen)
            if sniffed and self.plugin_context.output_sniffers:
                # observers are fire-and-forget on their own thread: a slow
                # or throwing sniffer must neither delay the batch's
                # responses nor overwrite a successful result
                self._sniffer_pool.submit(self._notify_sniffers, sniffed)
            return list(zip(outs, versions))

        finalize.timings = timings
        return finalize

    def _lane_predictions(
        self, lane: Lane, sup: list[Any], finalizers: list[Any]
    ) -> list[list[Any]]:
        """One lane's per-algorithm predictions with the batch -> per-query
        fallback: one poisonous query can't fail its batch mates."""
        preds_per_algo: list[list[Any]] = []
        for fin, (algo, model) in zip(
            finalizers, zip(lane.algorithms, lane.models)
        ):
            try:
                if fin is not None:
                    preds = list(fin())
                else:
                    preds = list(algo.predict_batch(model, sup))
                if len(preds) != len(sup):
                    raise RuntimeError(
                        f"predict_batch returned {len(preds)} results "
                        f"for {len(sup)} queries"
                    )
            except Exception:
                logger.exception(
                    "batched predict failed; falling back to per-query"
                )
                preds = []
                for s in sup:
                    try:
                        preds.append(algo.predict(model, s))
                    except Exception as exc:
                        logger.exception("query predict failed")
                        preds.append(exc)
            preds_per_algo.append(preds)
        return preds_per_algo

    def _serve_one(
        self, lane: Lane, query: Any, plist: list[Any], sniffed: list
    ) -> Any:
        """serve + output-blockers + encode for one query on one lane;
        raises the first per-query prediction failure."""
        for p in plist:
            if isinstance(p, BaseException):
                raise p
        result = lane.serving.serve(query, plist)
        result = self.plugin_context.apply_output_blockers(
            self.manifest.variant, query, result
        )
        sniffed.append((query, result))
        return Engine.encode_result(result)

    def _record_candidate_failure(self, version: str, gen: int | None = None) -> None:
        """Count one candidate failure against the breaker — unless the
        caller's rollout generation is stale (the work belongs to an
        already promoted/rolled-back candidate and must not trip the
        breaker of the current one)."""
        if gen is not None and gen != self._rollout_gen:
            return
        self._rollout_instruments.errors.inc(
            version=version, lane=LANE_CANDIDATE
        )
        self.candidate_breaker.record_failure()

    def _stable_retry(
        self, stable: Lane, query: Any, sniffed: list
    ) -> tuple[Any, str]:
        """Re-answer a candidate-lane query on the stable lane (single
        query path) so canary traffic never surfaces candidate errors."""
        inst = self._rollout_instruments
        inst.requests.inc(version=stable.version, lane=LANE_STABLE)
        try:
            s = stable.serving.supplement(query)
            plist = [
                algo.predict(model, s)
                for algo, model in zip(stable.algorithms, stable.models)
            ]
            return self._serve_one(stable, query, plist, sniffed), stable.version
        except Exception as exc:
            logger.exception("stable retry after candidate failure failed")
            inst.errors.inc(version=stable.version, lane=LANE_STABLE)
            return exc, stable.version

    def _submit_shadow(
        self, cand: Lane, pairs: list[tuple[Any, Any]], gen: int
    ) -> None:
        """Queue one batch for shadow scoring, bounded: a candidate slower
        than live traffic drops samples (counted) instead of growing the
        single-worker queue — and the memory it pins — without limit."""
        with self._shadow_lock:
            if self._shadow_pending >= self.config.shadow_max_backlog:
                self._rollout_instruments.shadow_dropped.inc(
                    len(pairs), version=cand.version
                )
                return
            self._shadow_pending += 1
        self._shadow_pool.submit(self._shadow_score, cand, pairs, gen)

    def _shadow_score(
        self, cand: Lane, pairs: list[tuple[Any, Any]], gen: int
    ) -> None:
        """Score the candidate on already-answered stable traffic (runs on
        the shadow thread, fully off the serving path): the candidate's
        answer is discarded, only the divergence/error record remains. A
        crashing candidate trips its breaker from here exactly as it would
        from the canary lane. Work queued for a rollout that has since
        ended (generation mismatch) is skipped wholesale — it must not
        feed the next candidate's breaker or counters."""
        inst = self._rollout_instruments
        discard: list = []
        try:
            for query, stable_body in pairs:
                if gen != self._rollout_gen:
                    return
                try:
                    t0 = time.perf_counter()
                    s = cand.serving.supplement(query)
                    plist = [
                        algo.predict(model, s)
                        for algo, model in zip(cand.algorithms, cand.models)
                    ]
                    body = self._serve_one(cand, query, plist, discard)
                    scored_s = time.perf_counter() - t0
                    if gen != self._rollout_gen:
                        return  # rollout ended while this query was scoring
                    # the latency gate needs candidate samples in shadow
                    # mode too, or a 10x-slower candidate would sail
                    # through on error/divergence alone (per-query single
                    # path here vs the canary's batched path — a rough but
                    # usable comparison basis)
                    inst.predict_seconds.observe(scored_s, version=cand.version)
                    inst.shadow_scored.inc(version=cand.version)
                    if _canonical_json(body) != _canonical_json(stable_body):
                        inst.divergence.inc(version=cand.version)
                    self.candidate_breaker.record_success()
                except Exception:
                    logger.exception("shadow scoring failed")
                    if gen != self._rollout_gen:
                        return
                    inst.shadow_scored.inc(version=cand.version)
                    inst.errors.inc(version=cand.version, lane=LANE_SHADOW)
                    self.candidate_breaker.record_failure()
        finally:
            with self._shadow_lock:
                self._shadow_pending -= 1

    def _notify_sniffers(self, sniffed: list) -> None:
        for query, result in sniffed:
            try:
                self.plugin_context.notify_output_sniffers(
                    self.manifest.variant, query, result
                )
            except Exception:
                logger.exception("output sniffer failed")

    @staticmethod
    def _unavailable(message: str, retry_after_s: float) -> web.Response:
        """503 with a Retry-After hint — the contract load balancers and
        well-behaved clients need to back off instead of hammering."""
        return web.json_response(
            {"message": message},
            status=503,
            headers={"Retry-After": str(max(1, round(retry_after_s)))},
        )

    def _spawn_bg(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _http(self):
        """The shared background-HTTP session, created lazily on the running
        loop with a total timeout (config.http_timeout_s) and closed by
        ``stop()``: a stalled collector now costs one bounded task, not an
        ever-growing pile of hung ones."""
        import aiohttp

        if self._http_session is None or self._http_session.closed:
            self._http_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.config.http_timeout_s)
            )
        return self._http_session

    async def _remote_log(self, message: str) -> None:
        """Ship a serving error to the remote collector: POST body is
        ``log_prefix`` + JSON of {engineInstance, message}
        (ref ``CreateServer.remoteLog``, CreateServer.scala:423-434)."""
        body = self.config.log_prefix + json.dumps(
            {"engineInstance": self.instance_id, "message": message}
        )
        try:
            async with self._http().post(self.config.log_url, data=body):
                pass  # response body unused; context exit releases the conn
        except Exception:
            logger.error("Unable to send remote log")

    async def _send_feedback(self, query: Any, prediction: Any) -> None:
        """POST a `predict` event back to the event server
        (ref CreateServer.scala:500-570)."""
        url = self.config.event_server_url
        key = self.config.feedback_access_key
        if not url or not key:
            return
        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": self.manifest.engine_id,
            "properties": {"query": query, "prediction": prediction},
        }
        try:
            async with self._http().post(
                f"{url}/events.json", params={"accessKey": key}, json=event
            ):
                pass
        except Exception:
            logger.exception("feedback POST failed")

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "alive",
                "engineId": self.manifest.engine_id,
                "engineVersion": self.manifest.version,
                "engineVariant": self.manifest.variant,
                "engineFactory": self.manifest.engine_factory,
                "engineInstanceId": self.instance_id,
                "modelVersion": self._active.version,
                "rollout": {
                    "mode": self._plan.mode,
                    "fraction": self._plan.fraction,
                    "candidate": (
                        self._candidate.version
                        if self._candidate is not None
                        else None
                    ),
                },
                "bandit": (
                    self.bandit.snapshot() if self.bandit is not None else None
                ),
                "startTime": self.start_time.isoformat(),
                "requestCount": self.request_count,
                "avgServingSec": self.avg_serving_sec,
                "lastServingSec": self.last_serving_sec,
                "resultCache": (
                    self._result_cache.stats()
                    if self._result_cache is not None
                    else None
                ),
                "latency": self._latency_summary_ms(),
                "batching": {
                    "batches": self._batcher.batches_dispatched,
                    "queries": self._batcher.queries_dispatched,
                    "avgBatchSize": (
                        self._batcher.queries_dispatched
                        / max(1, self._batcher.batches_dispatched)
                    ),
                },
                "resilience": self._resilience_snapshot(),
            }
        )

    def _latency_summary_ms(self) -> dict[str, Any]:
        """Legacy status-page latency block, derived from the SAME obs
        histogram /metrics exports (one source of truth; keys kept from
        the pre-registry LatencyHistogram). Counts every /queries.json
        answer including resilience 503s — the distribution an operator
        staring at `/` should see under load."""
        s = self._m_latency.summary(endpoint="/queries.json")
        if s["count"] == 0:
            return {"count": 0}
        return {
            "count": s["count"],
            "mean_ms": 1000.0 * s["mean"],
            "p50_ms": 1000.0 * s["p50"],
            "p95_ms": 1000.0 * s["p95"],
            "p99_ms": 1000.0 * s["p99"],
            "max_ms": 1000.0 * s["max"],
        }

    def _resilience_snapshot(self) -> dict[str, Any]:
        b = self._batcher
        return {
            "queueDepth": b.queue_depth,
            "queueHighWater": b.high_water,
            "watchdogTrips": b.watchdog_trips,
            "loadShedCount": b.shed_count,
            "breakers": {"dispatch": self.dispatch_breaker.snapshot()},
        }

    async def handle_healthz(self, request: web.Request) -> web.Response:
        """Readiness (distinct from the `/` liveness/status page): a load
        balancer drains this replica while the dispatch circuit is open or
        the admission queue is at high water, instead of sending traffic
        that would be shed."""
        snap = self._resilience_snapshot()
        shedding = (
            snap["queueHighWater"] > 0
            and snap["queueDepth"] >= snap["queueHighWater"]
        )
        ready = (
            not self._draining
            and not self._batcher._closed
            and not shedding
            and snap["breakers"]["dispatch"]["state"] != OPEN
        )
        return web.json_response(
            {"ready": ready, "draining": self._draining, **snap},
            status=200 if ready else 503,
        )

    async def handle_reload_get(self, request: web.Request) -> web.Response:
        """Deprecated GET spelling of /reload, kept for compat with old
        deploy scripts: a state-mutating GET is cacheable/prefetchable by
        intermediaries, which is how surprise reloads happen. Docs and
        tools all use POST."""
        logger.warning(
            "GET /reload is deprecated (state-mutating GET); use POST /reload"
        )
        return await self.handle_reload(request)

    async def handle_reload(self, request: web.Request) -> web.Response:
        """Swap in the latest COMPLETED instance (ref MasterActor reload).

        Serialized: two concurrent /reloads used to interleave their
        ``engine_params`` / ``_active`` / ``instance_id`` assignments and
        could leave the server announcing instance A while serving B's
        models. Under the lock, everything is loaded and warmed first and
        the three fields commit together only after that succeeds."""
        async with self._reload_lock:
            loop = asyncio.get_running_loop()
            latest = await loop.run_in_executor(
                None,
                lambda: self.storage.get_meta_data_engine_instances()
                .get_latest_completed(
                    self.manifest.engine_id,
                    self.manifest.version,
                    self.manifest.variant,
                ),
            )
            if latest is None:
                return web.json_response(
                    {"message": "no completed engine instance found"}, status=404
                )
            try:
                engine_params = self._engine_params_of(latest)
                models = await loop.run_in_executor(
                    None,
                    lambda: load_models_for_instance(
                        self.engine, engine_params, latest.id, storage=self.storage
                    ),
                )
                _, _, algorithms, serving = self.engine.make_components(
                    engine_params
                )
                # warm the NEW components before they take traffic (warmup
                # failures are non-fatal by the same contract as deploy-time
                # warmup: the first burst just pays its XLA compiles)
                await loop.run_in_executor(
                    None, self._warmup_components, algorithms, models
                )
                # blocking registry manifest scan stays off the event loop
                new_version = await loop.run_in_executor(
                    None, self._version_for_instance, latest.id
                )
            except Exception as exc:
                logger.exception("reload failed")
                return web.json_response({"message": str(exc)}, status=500)
            # commit: one consistent swap, nothing mutated on any failure path
            self.engine_params = engine_params
            with self._rollout_mutex:
                retired = self._active.version
                self._active = Lane(  # atomic swap
                    algorithms,
                    serving,
                    models,
                    new_version,
                    latest.id,
                    engine_params,
                )
                self.instance_id = latest.id
                cand = self._candidate
                if cand is not None:
                    # an active bake was comparing against the version that
                    # just got replaced: rebase the baseline on the new
                    # stable so the gates judge the candidate against what
                    # actually serves (the retired version's counters would
                    # freeze and collapse the error-rate allowance)
                    self.rollout_controller.begin(
                        new_version, cand.version, self._plan.mode
                    )
            # the registry-swap invalidation hook: the version that just
            # stopped serving must not answer another query from cache
            self._cache_flush(retired, f"reload -> {new_version}")
        logger.info("reloaded engine instance %s", latest.id)
        return web.json_response(
            {"message": "Reload successful", "instanceId": latest.id}
        )

    def _engine_params_of(self, instance: EngineInstance) -> EngineParams:
        return _engine_params_of_instance(self.engine, instance)

    # ------------------------------------------------- result cache plumbing
    def _cache_lookup_version(self) -> str | None:
        """The version whose cache lane may answer right now: the stable
        version when no rollout is active, None (= bypass) while one is.
        Canary users must exercise the candidate for the bake gates to
        mean anything, shadow mode needs dispatched stable answers to
        sample — and because candidate answers are never cached, a canary
        answer can never be served from a stale lane."""
        if self._candidate is not None or self._plan is not PLAN_OFF:
            return None
        return self._active.version

    def _cache_store(self, version: str, key: bytes, body: Any) -> None:
        """Insert one answered body (called by the batcher's finish path).
        Guarded at store time: only the CURRENT stable version's answers
        are cacheable — a swap or stage between dispatch and store
        orphans the write instead of caching across the boundary."""
        cache = self._result_cache
        if cache is None:
            return
        if self._candidate is not None or version != self._active.version:
            return
        cache.put(version, key, body)

    def _cache_flush(self, version: str | None, why: str) -> None:
        """Invalidate the affected lane's entries on a rollout transition
        (stage/promote/rollback) or reload. ``version=None`` clears all."""
        cache = self._result_cache
        if cache is None:
            return
        n = cache.clear() if version is None else cache.flush_version(version)
        if n:
            logger.info("result cache: flushed %d entries (%s)", n, why)

    def _collect_cache(self) -> None:
        """Scrape-time mirror of the cache's monotonic stats into the
        pio_cache_* counters (hits are also inc'd inline on the hot path;
        set_total clamps monotonic so the two sources can't fight)."""
        stats = self._result_cache.stats()
        self._m_cache_hits.set_total(stats["hits"])
        self._m_cache_misses.set_total(stats["misses"])
        self._m_cache_evictions.set_total(stats["evictions"])
        self._m_cache_invalidations.set_total(stats["invalidations"])

    @property
    def cache_hit_ratio(self) -> float:
        cache = self._result_cache
        return cache.hit_ratio if cache is not None else 0.0

    # ------------------------------------------------- progressive rollout
    def _version_for_instance(self, instance_id: str) -> str:
        """Registry version whose lineage points at this engine instance;
        the instance id itself when the registry doesn't know it."""
        if self.registry_store is not None:
            for m in self.registry_store.list_versions(self.manifest.engine_id):
                if m.instance_id == instance_id:
                    return m.version
        return instance_id

    def _on_candidate_transition(self, name: str, old: str, new: str) -> None:
        """Candidate breaker trip = the fast rollback path: no bake-window
        wait, the candidate lane is gone before the next batch forms.
        Fires on a dispatch/shadow thread; the rollback swap is mutex-
        guarded and pure attribute writes, so that is safe."""
        if new == OPEN:
            self._rollback_candidate("breaker-trip")
            # profile-on-alert: attach the host stacks (and optionally a
            # short device trace) that show WHAT the serving threads were
            # doing when the candidate died — off the dispatch thread, the
            # rollback must not wait for bundle file writes
            self._profile_on_alert(
                "breaker-trip", {"breaker": name, "from": old, "to": new}
            )

    def _profile_on_alert(self, trigger: str, context: dict[str, Any]) -> None:
        """Rate-limited background profile capture for alert paths; never
        blocks or raises into the caller (the alert path is already a
        failure path)."""
        if not self.config.profile_on_alert:
            return
        parts = self._profile_parts()
        texts = {"stacks_folded": self.sampler.folded()}
        threading.Thread(
            target=self.profiler.capture_alert,
            args=(trigger,),
            kwargs={"context": context, "parts": parts, "texts": texts},
            name="pio-profile-alert",
            daemon=True,
        ).start()

    def _check_slo_alerts(self) -> None:
        """SLO alert *transitions* capture a profile bundle (level
        triggers would re-fire every heartbeat of a long burn; the
        per-kind rate limiter bounds it anyway, but the transition is the
        incident). Rides the rollout heartbeat."""
        try:
            reports = self.slo.evaluate()
        except Exception:  # noqa: BLE001 - a broken SLO eval must not loop-kill
            return
        for rpt in reports:
            slo_name = rpt.get("name", "?")
            was = self._slo_alerting.get(slo_name, False)
            now_alerting = bool(rpt.get("alerting"))
            self._slo_alerting[slo_name] = now_alerting
            if now_alerting and not was:
                self._profile_on_alert(
                    "slo-alert",
                    {
                        "slo": slo_name,
                        "objective": rpt.get("objective"),
                        "compliance": rpt.get("compliance"),
                    },
                )

    def _bandit_tailer(self) -> RewardTailer:
        """Build the reward tail for one rollout: feedback events of the
        configured app, matched to served impressions by the trace id
        echoed into their properties. The cursor seeds at the current
        sequence head — historical events never retro-credit an arm."""
        from predictionio_tpu.data.store.event_store import resolve_app

        app_name = self.config.bandit_app_name
        if not app_name:
            raise ValueError(
                "bandit policy configured without bandit_app_name (the app "
                "whose events carry rewards)"
            )
        app_id, channel_id = resolve_app(
            self.storage, app_name, self.config.bandit_channel_name
        )
        return RewardTailer(
            self.storage.get_l_events(),
            app_id,
            channel_id,
            event_names=tuple(self.config.bandit_reward_events),
            trace_property=self.config.bandit_trace_property,
            reward_property=self.config.bandit_reward_property,
        )

    def _bandit_apply_fraction(self, fraction: float) -> None:
        """Move the live canary fraction to the policy's choice. The salt
        (candidate version) is untouched, so the sticky buckets stay
        fleet-consistent and a fraction change only flips the users whose
        bucket the boundary crossed."""
        with self._rollout_mutex:
            plan = self._plan
            if self._candidate is None or plan.mode != MODE_CANARY:
                return
            if abs(plan.fraction - fraction) < 1e-9:
                return
            self._plan = RolloutPlan(MODE_CANARY, fraction, plan.salt)
            plan = self._plan
        self._rollout_instruments.set_plan(plan)

    def stage_candidate_lane(
        self,
        lane: Lane,
        mode: str = MODE_CANARY,
        fraction: float = 0.1,
        persist: bool = True,
    ) -> None:
        """Install a candidate lane and begin the bake. The sticky salt is
        the candidate version, so every replica in a fleet canaries the
        same user population and a later rollout resamples a fresh one."""
        if mode not in (MODE_CANARY, MODE_SHADOW):
            raise ValueError(f"rollout mode must be canary|shadow, got {mode!r}")
        if lane.version == self._active.version:
            # canarying stable against itself would also desync server and
            # registry state (the store rejects it, and that rejection must
            # not be swallowed as bookkeeping noise)
            raise ValueError(f"{lane.version} is already the stable version")
        fraction = max(0.0, min(1.0, float(fraction)))
        with self._rollout_mutex:
            self._rollout_gen += 1  # orphan any in-flight work of the old bake
            self.candidate_breaker.reset()
            self._candidate = lane
            self._plan = RolloutPlan(
                mode, fraction if mode == MODE_CANARY else 0.0, lane.version
            )
            stable_version = self._active.version
            self.rollout_controller.begin(stable_version, lane.version, mode)
        if self.bandit is not None and mode == MODE_CANARY:
            # engage the two-arm bandit on this rollout; a persisted
            # posterior for the same version pair resumes. Failure (no
            # reward app resolvable, storage down) degrades to the plain
            # bake gate — never blocks the stage itself.
            try:
                self.bandit.begin(
                    stable_version, lane.version, self._bandit_tailer()
                )
            except Exception:
                logger.exception(
                    "bandit engage failed; plain bake gate governs this "
                    "rollout"
                )
        # a RE-staged candidate must not inherit entries from any earlier
        # life of its version (e.g. a prior bake followed by rollback);
        # lookups are bypassed for the whole bake anyway — this flush
        # guarantees the lane starts empty
        self._cache_flush(lane.version, f"stage {lane.version}")
        self._rollout_instruments.set_plan(self._plan)
        if persist and self.registry_store is not None:
            try:
                self.registry_store.stage_candidate(
                    self.manifest.engine_id,
                    lane.version,
                    mode=mode,
                    fraction=fraction,
                )
            except Exception:
                logger.exception("registry stage bookkeeping failed")
        logger.info(
            "staged candidate %s (%s, fraction %.3f)", lane.version, mode, fraction
        )

    def _promote_candidate(self, persist: bool = True) -> str | None:
        """Candidate becomes stable (atomic Lane swap). Returns the
        promoted version, or None when no candidate is staged.
        ``persist=False`` skips the registry write — the fleet-sync path
        uses it when the registry ALREADY records the promote (another
        replica's bake gate or the CLI did it first)."""
        with self._rollout_mutex:
            cand = self._candidate
            if cand is None:
                return None
            self._rollout_gen += 1
            retired = self._active.version
            self._active = cand
            if cand.instance_id:
                self.instance_id = cand.instance_id
            if cand.engine_params is not None:
                self.engine_params = cand.engine_params
            self._candidate = None
            self._plan = PLAN_OFF
            self.rollout_controller.end()
        # the retired stable's lane is the affected one: its entries stop
        # being addressable (lookups key on the NEW stable) — flush them
        # so nothing lingers in memory either
        self._cache_flush(retired, f"promote {cand.version}")
        self._rollout_instruments.set_plan(PLAN_OFF)
        self._rollout_instruments.promotions.inc()
        if self.bandit is not None and self.bandit.active:
            self.bandit.end("promote")
        if persist and self.registry_store is not None:
            try:
                self.registry_store.promote(self.manifest.engine_id, cand.version)
            except Exception:
                logger.exception("registry promote bookkeeping failed")
        logger.info("promoted candidate %s to stable", cand.version)
        return cand.version

    def _rollback_candidate(
        self, reason: str, detail: str = "", persist: bool = True
    ) -> str | None:
        """Drop the candidate lane; stable keeps serving untouched.
        ``reason`` is a short label (breaker-trip/manual/error-rate/
        latency/divergence/fleet-sync — bounded metric cardinality),
        ``detail`` the human sentence for logs and registry history.
        ``persist=False``: registry already reflects the rollback (the
        fleet-sync path reacting to another process's unstage)."""
        with self._rollout_mutex:
            cand = self._candidate
            if cand is None:
                return None
            self._rollout_gen += 1
            self._candidate = None
            self._plan = PLAN_OFF
            self.rollout_controller.end()
        # the candidate lane is the affected one (stable entries stay
        # valid — stable never changed); candidate answers are never
        # cached, so this is belt-and-braces against any future path that
        # would put them there
        self._cache_flush(cand.version, f"rollback {cand.version} ({reason})")
        self._rollout_instruments.set_plan(PLAN_OFF)
        self._rollout_instruments.rollbacks.inc(reason=reason)
        if self.bandit is not None and self.bandit.active:
            self.bandit.end(
                "retire" if reason == "bandit-retire" else "rollback"
            )
        if persist and self.registry_store is not None:
            try:
                # unstage, never rollback: the store's rollback falls back
                # to reverting the stable pin when no candidate is recorded
                # (e.g. the stage write was swallowed), which would desync
                # the registry from what this server actually serves
                self.registry_store.unstage(
                    self.manifest.engine_id,
                    reason=(f"{reason}: {detail}" if detail else reason),
                )
            except Exception:
                logger.exception("registry rollback bookkeeping failed")
        logger.warning(
            "candidate %s rolled back (%s) %s", cand.version, reason, detail
        )
        return cand.version

    def _load_lane_from_registry(self, version: str) -> Lane:
        """Registry artifact -> servable Lane: verified blob, deserialize,
        prepare_deploy, fresh components, warmup. Blocking — run in an
        executor. Engine params come from the lineage manifest's engine
        instance when the metadata store still has it."""
        store = self.registry_store
        if store is None:
            raise RuntimeError("no model registry configured (registry_dir)")
        manifest = store.get_manifest(self.manifest.engine_id, version)
        if manifest is None:
            raise ValueError(f"unknown model version {version!r}")
        blob = store.load_blob(self.manifest.engine_id, version)
        persisted = model_io.deserialize_models(blob)
        engine_params = self.engine_params
        if manifest.instance_id:
            instance = self.storage.get_meta_data_engine_instances().get(
                manifest.instance_id
            )
            if instance is not None:
                engine_params = self._engine_params_of(instance)
        ctx = WorkflowContext(mode="serving", _storage=self.storage)
        models = self.engine.prepare_deploy(ctx, engine_params, persisted)
        # pin the version's ANN index (if the manifest carries one) onto
        # the model object BEFORE warmup compiles the serving programs
        ann_lifecycle.attach_from_registry(
            store, self.manifest.engine_id, version, models
        )
        _, _, algorithms, serving = self.engine.make_components(engine_params)
        self._warmup_components(algorithms, models)
        return Lane(
            algorithms, serving, models, version, manifest.instance_id, engine_params
        )

    async def _rollout_loop(self) -> None:
        """Controller heartbeat: evaluate the bake gates on a cadence and
        apply the verdict. Promotion takes the reload lock so it can never
        interleave with a /reload commit."""
        while True:
            await asyncio.sleep(self.config.bake_check_interval_s)
            try:
                # profile-on-alert rides the same heartbeat: SLO alert
                # transitions capture host stacks (the eval is counter
                # math; the capture itself runs on its own thread)
                self._check_slo_alerts()
                await self._rollout_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("rollout controller tick failed")

    async def _rollout_tick(self) -> None:
        if self._candidate is None:
            return
        verdict, reason = self.rollout_controller.evaluate()
        loop = asyncio.get_running_loop()
        # the bake gate's health veto outranks everything, bandit or not: a
        # reward-winning arm that 5xxes or blows the latency ratio still
        # rolls back through the same path
        if verdict == VERDICT_ROLLBACK:
            # "error-rate gate: ..." -> label "error-rate", detail = full text
            await loop.run_in_executor(
                None, self._rollback_candidate, reason.split(" ")[0], reason
            )
            return
        bandit = self.bandit
        if bandit is None or not bandit.active:
            # plain PR-4 bake gate: time + health decide
            if verdict == VERDICT_PROMOTE:
                async with self._reload_lock:
                    version = await loop.run_in_executor(
                        None, self._promote_candidate
                    )
                if version:
                    logger.info("auto-promoted %s: %s", version, reason)
            return
        # bandit engaged: the bake gate doubles as reward accounting. The
        # tick drains feedback from the event store (blocking reads:
        # executor), credits the posteriors, and the policy re-chooses the
        # live traffic fraction. The REWARD posterior owns promote/retire;
        # the controller's promote verdict acts as the health+window
        # precondition (both evidence floors must clear).
        decision = await loop.run_in_executor(None, bandit.tick)
        if decision is None:
            return  # rollout flipped underneath the tick
        if decision.verdict == DECIDE_PROMOTE and verdict == VERDICT_PROMOTE:
            async with self._reload_lock:
                version = await loop.run_in_executor(
                    None, self._promote_candidate
                )
            if version:
                logger.info("bandit promoted %s: %s", version, decision.reason)
        elif decision.verdict == DECIDE_RETIRE:
            await loop.run_in_executor(
                None,
                self._rollback_candidate,
                "bandit-retire",
                decision.reason,
            )
        else:
            self._bandit_apply_fraction(decision.fraction)

    # ------------------------------------------- fleet registry coordination
    async def _registry_sync_loop(self) -> None:
        """Fleet heartbeat (docs/fleet.md): poll the registry's cheap
        ``state_generation()`` and reconcile local lanes whenever another
        process moved it — a promote/rollback/stage issued through ANY
        replica, the gateway, or the CLI propagates to every worker, and
        each per-process result cache flushes on the transition."""
        while True:
            await asyncio.sleep(self.config.registry_sync_interval_s)
            try:
                await self._registry_sync_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("registry sync tick failed")

    async def _registry_sync_tick(self) -> None:
        store = self.registry_store
        if store is None:
            return
        loop = asyncio.get_running_loop()
        # generation probe is ONE small-file read — the cadence can be
        # aggressive without scanning manifests every tick
        gen = await loop.run_in_executor(
            None, store.state_generation, self.manifest.engine_id
        )
        if gen == self._seen_state_gen:
            return
        # the reload lock serializes against HTTP-driven stage/promote/
        # rollback and /reload, so reconciliation never interleaves with a
        # locally-initiated transition half-way through its own commit
        async with self._reload_lock:
            state = await loop.run_in_executor(
                None, store.get_state, self.manifest.engine_id
            )
            if await self._reconcile_registry_state(state):
                self._seen_state_gen = state.generation
            # else: a lane failed to load (transient I/O, artifact not yet
            # visible) — leave the seen generation behind so the NEXT tick
            # retries instead of never adopting this transition

    async def _reconcile_registry_state(self, state) -> bool:
        """Make local serving lanes match the registry's rollout state.
        Local transitions (which wrote that state themselves) reconcile to
        a no-op; remote ones are adopted without re-persisting. Returns
        False when a referenced version could not be loaded — the caller
        must retry the same generation on its next tick."""
        loop = asyncio.get_running_loop()
        # 1) the stable pin moved
        if state.stable and state.stable != self._active.version:
            cand = self._candidate
            if cand is not None and cand.version == state.stable:
                # another replica's bake gate promoted the candidate we
                # are baking: same lane objects, just swap locally
                await loop.run_in_executor(None, self._promote_candidate, False)
                logger.info("fleet-sync: adopted promote of %s", state.stable)
            else:
                try:
                    lane = await loop.run_in_executor(
                        None, self._load_lane_from_registry, state.stable
                    )
                except Exception:
                    logger.exception(
                        "fleet-sync: pinned stable %s unloadable; still "
                        "serving %s",
                        state.stable,
                        self._active.version,
                    )
                    return False
                self._adopt_stable(lane)
        # 2) the candidate changed
        cand = self._candidate
        if state.candidate and state.candidate != self._active.version:
            plan_changed = cand is not None and (
                self._plan.mode != state.mode
                or (
                    state.mode == MODE_CANARY
                    and abs(self._plan.fraction - state.fraction) > 1e-9
                )
            )
            if cand is not None and cand.version == state.candidate and not plan_changed:
                return True  # already baking exactly this rollout
            if cand is not None and cand.version == state.candidate:
                lane = cand  # plan change only: reuse the loaded lane
            else:
                try:
                    lane = await loop.run_in_executor(
                        None, self._load_lane_from_registry, state.candidate
                    )
                except Exception:
                    logger.exception(
                        "fleet-sync: staged candidate %s unloadable",
                        state.candidate,
                    )
                    return False
            await loop.run_in_executor(
                None,
                lambda: self.stage_candidate_lane(
                    lane,
                    mode=state.mode,
                    fraction=state.fraction,
                    persist=False,
                ),
            )
            logger.info(
                "fleet-sync: adopted staged candidate %s (%s)",
                state.candidate,
                state.mode,
            )
        elif not state.candidate and cand is not None:
            # unstaged/rolled back elsewhere (possibly by a peer's breaker
            # trip): drop the local lane too, without re-persisting
            await loop.run_in_executor(
                None,
                lambda: self._rollback_candidate(
                    "fleet-sync",
                    "registry candidate cleared by another process",
                    persist=False,
                ),
            )
        return True

    def _adopt_stable(self, lane: Lane) -> None:
        """Swap in a stable version pinned by another process — /reload's
        commit semantics (atomic Lane swap, retired lane's cache entries
        flushed) without the metadata-store resolution. A local bake in
        flight is rebased on the new stable, exactly like /reload."""
        with self._rollout_mutex:
            self._rollout_gen += 1
            retired = self._active.version
            self._active = lane
            if lane.instance_id:
                self.instance_id = lane.instance_id
            if lane.engine_params is not None:
                self.engine_params = lane.engine_params
            cand = self._candidate
            if cand is not None:
                self.rollout_controller.begin(
                    lane.version, cand.version, self._plan.mode
                )
        self._cache_flush(retired, f"fleet-sync stable -> {lane.version}")
        logger.info(
            "fleet-sync: adopted stable %s (was %s)", lane.version, retired
        )

    def _models_snapshot(self) -> dict[str, Any]:
        stable = self._active
        cand = self._candidate
        plan = self._plan
        inst = self._rollout_instruments

        def lane_json(lane: Lane) -> dict[str, Any]:
            return {
                "version": lane.version,
                "instanceId": lane.instance_id,
                "counters": inst.lane_counts(lane.version),
                "p95PredictMs": round(inst.p95_seconds(lane.version) * 1e3, 3),
            }

        out: dict[str, Any] = {
            "stable": lane_json(stable),
            "candidate": lane_json(cand) if cand is not None else None,
            "mode": plan.mode,
            "fraction": plan.fraction,
            "stickyKeyField": self.config.sticky_key_field,
            "candidateBreaker": self.candidate_breaker.snapshot(),
            "controller": self.rollout_controller.snapshot(),
        }
        if self.registry_store is not None:
            state = self.registry_store.get_state(self.manifest.engine_id)
            out["registry"] = {
                "dir": self.registry_store.base_dir,
                # the fleet-coordination change detector, surfaced so
                # dashboards and peers can watch for cross-process moves
                # without reading the whole state
                "stateGeneration": state.generation,
                "state": state.to_json_dict(),
                "versions": [
                    m.summary_row()
                    for m in self.registry_store.list_versions(
                        self.manifest.engine_id
                    )
                ],
            }
        return out

    async def handle_models(self, request: web.Request) -> web.Response:
        """What serves, what bakes, what the controller thinks — the JSON
        behind `pio models show --url` and the dashboard's rollout panel.
        The snapshot scans registry manifests on disk: executor, not event
        loop — a dashboard polling /models on a slow volume must never
        stall /queries.json ingress."""
        snapshot = await asyncio.get_running_loop().run_in_executor(
            None, self._models_snapshot
        )
        return web.json_response(snapshot)

    async def handle_models_candidate(self, request: web.Request) -> web.Response:
        """Stage a registry version as the rollout candidate."""
        try:
            body = await request.json()
            version = str(body["version"])
            mode = body.get("mode", MODE_CANARY)
            fraction = float(body.get("fraction", 0.1))
        except Exception:
            return web.json_response(
                {"message": "body must be JSON with a 'version' key"}, status=400
            )
        async with self._reload_lock:
            try:
                loop = asyncio.get_running_loop()
                lane = await loop.run_in_executor(
                    None, self._load_lane_from_registry, version
                )
                # staging persists registry state (fsync'd write): executor
                await loop.run_in_executor(
                    None,
                    lambda: self.stage_candidate_lane(
                        lane, mode=mode, fraction=fraction
                    ),
                )
            except (ValueError, RuntimeError) as exc:
                return web.json_response({"message": str(exc)}, status=400)
            except Exception as exc:
                logger.exception("staging candidate failed")
                return web.json_response({"message": str(exc)}, status=500)
        return web.json_response(
            {
                "message": "Candidate staged",
                "version": version,
                "mode": mode,
                "fraction": fraction,
            }
        )

    async def handle_models_promote(self, request: web.Request) -> web.Response:
        """Promote the staged candidate. An explicit ``{"version": ...}``
        in the body is a guard, not a selector: it must name the staged
        candidate, or nothing happens (409) — silently promoting whatever
        is staged when the operator asked for a specific version is how
        the wrong model ships."""
        requested = None
        if request.can_read_body:
            try:
                requested = (await request.json()).get("version")
            except Exception:
                pass
        async with self._reload_lock:
            if requested is not None:
                cand = self._candidate
                if cand is None or cand.version != requested:
                    staged = cand.version if cand is not None else "none"
                    return web.json_response(
                        {
                            "message": (
                                f"version {requested} is not the staged "
                                f"candidate (staged: {staged})"
                            )
                        },
                        status=409,
                    )
            version = await asyncio.get_running_loop().run_in_executor(
                None, self._promote_candidate
            )
        if version is None:
            return web.json_response(
                {"message": "no candidate staged"}, status=404
            )
        return web.json_response(
            {
                "message": "Promoted",
                "version": version,
                "instanceId": self.instance_id,
            }
        )

    async def handle_models_rollback(self, request: web.Request) -> web.Response:
        version = await asyncio.get_running_loop().run_in_executor(
            None, self._rollback_candidate, "manual"
        )
        if version is None:
            return web.json_response(
                {"message": "no candidate staged"}, status=404
            )
        return web.json_response({"message": "Rolled back", "version": version})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition: request latency histogram, phase
        waterfall, queue depth, shed/deadline/watchdog counters, breaker
        state, jit recompile count — everything `pio top` and a Prometheus
        scrape need. OpenMetrics negotiation (Accept header or
        ``?exemplars=1``) adds per-bucket trace-id exemplars."""
        return metrics_response(self.metrics, request)

    async def handle_slo(self, request: web.Request) -> web.Response:
        """Burn-rate report for the declared objectives plus the phase
        waterfall summary — the JSON behind the `pio top` SLO line."""
        body = self.slo.report()
        body["phases"] = self.waterfall.snapshot()
        return web.json_response(body)

    async def handle_traces_recent(self, request: web.Request) -> web.Response:
        return traces_response(self.tracer, request)

    async def handle_profile_capture(self, request: web.Request) -> web.Response:
        """On-demand device capture: ``POST /profile/capture?ms=``. The
        duration is clamped to the configured rails; a capture already in
        flight answers 409 (single-flight — jax keeps one global trace
        session per process). The blocking body (trace sleep + bundle
        writes) runs on an executor, never on the event loop."""
        raw_ms = request.query.get("ms")
        try:
            ms = int(raw_ms) if raw_ms is not None else None
        except ValueError:
            return web.json_response(
                {"message": "ms must be an integer"}, status=400
            )
        loop = asyncio.get_running_loop()
        try:
            path = await loop.run_in_executor(
                None, self._capture_profile, ms, "manual"
            )
        except ProfileBusyError:
            return web.json_response(
                {"message": "a profile capture is already in flight"},
                status=409,
            )
        except Exception as exc:  # noqa: BLE001 - surface, don't 500-blank
            logger.exception("profile capture failed")
            return web.json_response(
                {"message": f"capture failed: {exc}"}, status=500
            )
        return web.json_response(
            {
                "bundle": os.path.basename(path),
                "path": path,
                "durationMs": self.profiler.clamp_ms(ms),
                "modelVersion": self.model_version,
            }
        )

    async def handle_profile_stacks(self, request: web.Request) -> web.Response:
        """The always-on sampler's folded stacks: flamegraph-ready folded
        text by default (``stack count`` lines, pipe into flamegraph.pl),
        the structured snapshot + hotspot table with ``?format=json``
        (what ``pio top --hotspots`` consumes)."""
        if request.query.get("format") == "json":
            body = self.sampler.snapshot()
            body["hotspots"] = self.sampler.hotspots()
            return web.json_response(body)
        return web.Response(
            text=self.sampler.folded(), content_type="text/plain"
        )

    async def handle_stop(self, request: web.Request) -> web.Response:
        self._stop_event.set()
        return web.json_response({"message": "Stopping."})

    async def handle_plugins(self, request: web.Request) -> web.Response:
        return web.json_response(self.plugin_context.to_json_dict())

    # ------------------------------------------------------------------- app
    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_status),
                web.get("/healthz", self.handle_healthz),
                web.get("/metrics", self.handle_metrics),
                web.get("/slo", self.handle_slo),
                web.get("/traces/recent", self.handle_traces_recent),
                web.post("/queries.json", self.handle_queries),
                # POST is the contract (CreateServer.scala:618-626); the GET
                # spelling still works but logs a deprecation warning
                web.post("/reload", self.handle_reload),
                web.get("/reload", self.handle_reload_get),
                # model registry / progressive rollout surface
                web.get("/models", self.handle_models),
                web.post("/models/candidate", self.handle_models_candidate),
                web.post("/models/promote", self.handle_models_promote),
                web.post("/models/rollback", self.handle_models_rollback),
                web.post("/stop", self.handle_stop),
                web.get("/stop", self.handle_stop),
                web.get("/plugins.json", self.handle_plugins),
                # profiling plane (docs/observability.md §Profiling plane)
                web.post("/profile/capture", self.handle_profile_capture),
                web.get("/profile/stacks", self.handle_profile_stacks),
            ]
        )

        async def _start_rollout_loop(app: web.Application) -> None:
            if self.config.sampler_period_s > 0:
                self.sampler.start()
            self._rollout_task = asyncio.ensure_future(self._rollout_loop())
            if (
                self.registry_store is not None
                and self.config.registry_sync_interval_s > 0
            ):
                self._registry_sync_task = asyncio.ensure_future(
                    self._registry_sync_loop()
                )

        async def _close_batcher(app: web.Application) -> None:
            self.sampler.stop()
            tasks = [self._rollout_task, self._registry_sync_task]
            self._rollout_task = None
            self._registry_sync_task = None
            for task in tasks:
                if task is not None:
                    task.cancel()
            live = [t for t in tasks if t is not None]
            if live:
                await asyncio.gather(*live, return_exceptions=True)
            # cancel the collect loop while its event loop is still alive
            # (otherwise the pending task leaks a "loop is closed" warning)
            self._batcher.close()
            await self._batcher.wait_closed()
            await self._close_background()

        app.on_startup.append(_start_rollout_loop)
        app.on_cleanup.append(_close_batcher)
        return app

    async def _close_background(self) -> None:
        """Cancel fire-and-forget tasks and close the shared HTTP session —
        the 'zero hung asyncio tasks after shutdown' half of the resilience
        contract."""
        for task in list(self._bg_tasks):
            task.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        self._bg_tasks.clear()
        if self._http_session is not None and not self._http_session.closed:
            await self._http_session.close()
        self._http_session = None

    @property
    def algorithms(self) -> list[Any]:
        return self._active.algorithms

    @property
    def serving(self) -> Any:
        return self._active.serving

    @property
    def models(self) -> list[Any]:
        return self._active.models

    @property
    def model_version(self) -> str:
        return self._active.version

    def _warmup(self) -> None:
        """Pre-compile serving programs (pow2 batch buckets etc.) so the
        first traffic burst after deploy/reload pays no XLA compiles."""
        lane = self._active
        self._warmup_components(lane.algorithms, lane.models)

    def _collect_ann_indexes(self) -> None:
        candidate = self._candidate
        self.ann_instruments.sync_indexes(
            ann_lifecycle.pinned_indexes(
                [self._active.models]
                + ([candidate.models] if candidate is not None else [])
            )
        )

    def _warmup_components(self, algorithms: list[Any], models: list[Any]) -> None:
        # late-bind any registry-attached ANN index to this server's
        # pio_ann_* instruments (the lane loader runs before the metrics
        # registry is in scope). The ANN search buckets warm inside each
        # engine's warmup_serving below — each engine compiles exactly
        # the kernel variant its dispatch actually runs (exclusion /
        # composed-tower), not the generic one
        ann_lifecycle.bind_instruments(models, self.ann_instruments)
        for algo, model in zip(algorithms, models):
            try:
                algo.warmup_serving(model, self.config.max_batch_size)
            except Exception:
                logger.exception("serving warmup failed (continuing)")
        # baseline the compile watcher AFTER warmup: the compiles warmup
        # just paid for are intentional; only compiles past this point are
        # serving-time recompiles worth alarming on
        try:
            self.compile_watcher.sample()
        except Exception:
            logger.exception("compile watcher baseline failed (continuing)")

    async def start(self) -> None:
        await asyncio.get_running_loop().run_in_executor(None, self._warmup)
        retries = max(1, self.config.bind_retries)
        last_error: Exception | None = None
        for attempt in range(retries):
            # fresh runner+site per attempt: a TCPSite cannot be re-started
            # after a failed bind (it stays registered with the runner).
            # access_log=None: per-request access-line formatting is host
            # glue on the respond phase; request accounting is owned by
            # the metrics registry + waterfall instead
            self._runner = web.AppRunner(self.make_app(), access_log=None)
            await self._runner.setup()
            site = web.TCPSite(
                self._runner,
                self.config.ip,
                self.config.port,
                ssl_context=self.config.ssl_context(),
            )
            try:
                await site.start()
                break
            except OSError as exc:  # bind retry (ref MasterActor x3)
                last_error = exc
                await self._runner.cleanup()
                self._runner = None
                logger.warning(
                    "bind %s:%d failed (attempt %d/%d): %s",
                    self.config.ip,
                    self.config.port,
                    attempt + 1,
                    retries,
                    exc,
                )
                if attempt + 1 < retries:
                    await asyncio.sleep(1.0)
        else:
            raise last_error  # type: ignore[misc]
        logger.info("engine server on %s:%d", self.config.ip, self.config.port)

    async def drain(self) -> None:
        """Graceful drain (the SIGTERM path): stop accepting, let the
        micro-batcher flush and every in-flight request answer, then
        return — so a supervised restart or rolling redeploy is 5xx-free
        even without a gateway in front.

        Order matters: (1) mark draining so /healthz goes unready and a
        load balancer routes around us; (2) close the listener — NEW
        connections are refused at the TCP level (the client/gateway
        retries elsewhere), which is not a 5xx; (3) wait out the
        admission queue + dispatch pipeline + handler tail, bounded by
        ``drain_grace_s``. The batcher keeps running the whole time, so
        queued queries dispatch and answer normally. Idempotent."""
        if self._draining:
            return
        self._draining = True
        logger.info("drain: listener closing, answering in-flight requests")
        if self._runner is not None:
            for site in list(self._runner.sites):
                try:
                    await site.stop()
                except Exception:
                    logger.exception("drain: site stop failed (continuing)")
        deadline = time.perf_counter() + max(0.0, self.config.drain_grace_s)
        b = self._batcher
        while time.perf_counter() < deadline:
            if (
                self._inflight_requests == 0
                and b.queue_depth == 0
                and not b._finish_tasks
            ):
                break
            await asyncio.sleep(0.02)
        leftover = self._inflight_requests
        if leftover:
            logger.warning(
                "drain grace (%.1fs) expired with %d requests in flight",
                self.config.drain_grace_s,
                leftover,
            )
        else:
            logger.info("drain complete: zero requests in flight")

    def begin_drain(self) -> None:
        """Signal-handler entry (``loop.add_signal_handler`` callbacks
        must not block): drain, then release ``run_until_stopped``. The
        task is held on its own attribute — ``stop()``'s background-task
        sweep only runs after the drain has already set the stop event,
        so the drain can never be cancelled by the shutdown it causes."""

        async def _go() -> None:
            await self.drain()
            self._stop_event.set()

        self._drain_task = asyncio.ensure_future(_go())

    async def stop(self) -> None:
        self._batcher.close()
        await self._batcher.wait_closed()
        self._sniffer_pool.shutdown(wait=False, cancel_futures=True)
        self._shadow_pool.shutdown(wait=False, cancel_futures=True)
        await self._close_background()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def run_until_stopped(self) -> None:
        await self.start()
        await self._stop_event.wait()
        await self.stop()


def _engine_params_of_instance(engine: Engine, instance: EngineInstance) -> EngineParams:
    variant = {
        "datasource": {"params": json.loads(instance.data_source_params or "{}")},
        "preparator": {"params": json.loads(instance.preparator_params or "{}")},
        "algorithms": json.loads(instance.algorithms_params or "[]"),
        "serving": {"params": json.loads(instance.serving_params or "{}")},
    }
    return engine.engine_params_from_variant(variant)


def create_query_server(
    engine_dir: str,
    variant_path: str | None = None,
    storage: Storage | None = None,
    config: ServerConfig | None = None,
    instance_id: str | None = None,
) -> QueryServer:
    """Build a server for the engine dir. With a registry configured, the
    registry's pinned *stable* version is the source of truth for what
    serves (docs/DECISIONS.md — the instances table is the training
    ledger); without one, or when the registry can't be read, the latest
    COMPLETED instance is resolved exactly as the reference did
    (ref commands/Engine.deploy :207-242)."""
    storage = storage or Storage.instance()
    config = config or ServerConfig()
    manifest, engine = load_engine(engine_dir, variant_path)
    store = ArtifactStore(config.registry_dir) if config.registry_dir else None
    instances = storage.get_meta_data_engine_instances()
    if store is not None and not instance_id:
        state = store.get_state(manifest.engine_id)
        if state.stable:
            try:
                return _query_server_from_registry(
                    engine, manifest, store, state.stable, storage, config
                )
            except Exception:
                logger.exception(
                    "registry stable %s unusable; falling back to the "
                    "latest COMPLETED instance",
                    state.stable,
                )
    if instance_id:
        instance = instances.get(instance_id)
        if instance is None:
            raise RuntimeError(f"engine instance {instance_id} not found")
    else:
        instance = instances.get_latest_completed(
            manifest.engine_id, manifest.version, manifest.variant
        )
        if instance is None:
            raise RuntimeError(
                f"no COMPLETED engine instance for {manifest.engine_id} "
                f"{manifest.version} {manifest.variant}; run train first"
            )
    engine_params = engine.engine_params_from_variant(manifest.variant_json)
    ctx = WorkflowContext(mode="serving", _storage=storage)
    models = load_models_for_instance(
        engine, engine_params, instance.id, ctx=ctx, storage=storage
    )
    return QueryServer(
        engine=engine,
        engine_params=engine_params,
        models=models,
        manifest=manifest,
        instance_id=instance.id,
        storage=storage,
        config=config,
        registry_store=store,
    )


def _query_server_from_registry(
    engine: Engine,
    manifest: EngineManifest,
    store: ArtifactStore,
    version: str,
    storage: Storage,
    config: ServerConfig,
) -> QueryServer:
    """Deploy the registry's stable version: verified blob -> deserialize
    -> prepare_deploy, params from the lineage manifest's instance."""
    reg_manifest = store.get_manifest(manifest.engine_id, version)
    if reg_manifest is None:
        raise RuntimeError(f"registry stable {version} has no manifest")
    blob = store.load_blob(manifest.engine_id, version)
    persisted = model_io.deserialize_models(blob)
    engine_params = None
    if reg_manifest.instance_id:
        instance = storage.get_meta_data_engine_instances().get(
            reg_manifest.instance_id
        )
        if instance is not None:
            engine_params = _engine_params_of_instance(engine, instance)
    if engine_params is None:
        engine_params = engine.engine_params_from_variant(manifest.variant_json)
    ctx = WorkflowContext(mode="serving", _storage=storage)
    models = engine.prepare_deploy(ctx, engine_params, persisted)
    ann_lifecycle.attach_from_registry(store, manifest.engine_id, version, models)
    logger.info(
        "deploying registry stable %s (instance %s)",
        version,
        reg_manifest.instance_id or "?",
    )
    return QueryServer(
        engine=engine,
        engine_params=engine_params,
        models=models,
        manifest=manifest,
        instance_id=reg_manifest.instance_id or version,
        storage=storage,
        config=config,
        registry_store=store,
        model_version=version,
    )


def _maybe_install_uvloop() -> bool:
    """Swap in uvloop when available (PIO_UVLOOP=0 opts out): the query
    hot path is event-loop-bound once device work is micro-batched, and
    uvloop's C event loop shaves the per-request asyncio overhead. A
    missing uvloop is silently fine — it is optional by contract (the
    container image must not need it)."""
    import os

    if os.environ.get("PIO_UVLOOP", "1").lower() in ("0", "false", "no"):
        return False
    try:
        import uvloop  # type: ignore[import-not-found]
    except ImportError:
        return False
    uvloop.install()
    logger.info("uvloop installed for the query server event loop")
    return True


def run_query_server(
    engine_dir: str,
    variant_path: str | None = None,
    config: ServerConfig | None = None,
) -> None:
    _maybe_install_uvloop()
    server = create_query_server(engine_dir, variant_path, config=config)

    async def main():
        import signal

        # SIGTERM = graceful drain, not teardown-with-requests-in-flight:
        # the listener closes, the micro-batcher flushes, in-flight
        # queries answer, THEN the process exits — what a supervisor's
        # rolling restart (fleet/supervisor.py) relies on for zero 5xx
        try:
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGTERM, server.begin_drain
            )
        except (NotImplementedError, RuntimeError):
            pass  # loop without signal support: default SIGTERM applies
        await server.run_until_stopped()

    asyncio.run(main())
