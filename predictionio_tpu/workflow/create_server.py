"""Engine (query) server — the deploy surface.

Reference parity: ``core/.../workflow/CreateServer.scala`` —
  POST /queries.json  (:464-616): decode query -> serving.supplement ->
                      per-algorithm predict -> serving.serve -> JSON;
                      optional async feedback POST of a `predict` event
                      (entityType ``pio_pr``, prId) to the event server
                      (:500-570); per-request latency bookkeeping (:578-585).
  GET /               engine status incl. requestCount / avgServingSec /
                      lastServingSec (:385-420).
  GET /reload         hot-swap to the latest COMPLETED engine instance
                      (MasterActor :317-343).
  POST/GET /stop      graceful undeploy (used by the CLI's undeploy).
  GET /plugins.json   engine-server plugin inventory.

TPU notes: models are re-laid-out on device once at (re)load via
``Engine.prepare_deploy``; the predict path calls resident jitted functions
(e.g. the ALS top-k) so a request does one small host->device transfer and
one device->host top-k readback. Serving latency histogram kept in-process
(the measurement machinery BASELINE.md requires).
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime as _dt
import json
import logging
import time
from typing import Any, NamedTuple

from aiohttp import web

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.data.storage.base import EngineInstance
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs.jaxprof import CompileWatcher
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import (
    TRACE_HEADER,
    Tracer,
    current_trace_id,
    get_tracer,
    mint_trace_id,
    reset_trace_id,
    set_trace_id,
)
from predictionio_tpu.obs.web import (
    BreakerInstruments,
    metrics_response,
    traces_response,
)
from predictionio_tpu.resilience import (
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
)
from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.core_workflow import load_models_for_instance
from predictionio_tpu.workflow.engine_loader import EngineManifest, load_engine

logger = logging.getLogger(__name__)
UTC = _dt.timezone.utc


class LoadShedError(RuntimeError):
    """Admission control rejected the request (queue over high water).

    Not transient in-process: the server is telling the *client* to back
    off (`Retry-After`), not asking itself to retry into the same queue.
    """

    transient = False

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ShuttingDownError(RuntimeError):
    """The server is stopping; in-flight and new requests answer 503."""

    transient = False

    def __init__(self):
        super().__init__("query server is shutting down")


@dataclasses.dataclass
class ServerConfig:
    ip: str = "0.0.0.0"
    port: int = 8000
    accesskey: str | None = None  # optional auth for /queries.json
    feedback: bool = False
    event_server_url: str | None = None  # e.g. http://localhost:7070
    feedback_access_key: str | None = None
    # TLS (ref common/SSLConfiguration.scala): PEM cert + key paths
    ssl_certfile: str | None = None
    ssl_keyfile: str | None = None
    bind_retries: int = 3  # ref MasterActor bind retry x3 (CreateServer.scala:348)
    # remote log shipping of serving errors (ref CreateServer.scala:423-434,
    # 595-611): POST log_prefix + JSON{engineInstance, message} to log_url
    log_url: str | None = None
    log_prefix: str = ""
    # serving micro-batch dispatch: concurrent /queries.json requests are
    # coalesced into one algorithm.predict_batch call (the reference predicts
    # per-request on an actor and carries a literal ``TODO: Parallelize``,
    # CreateServer.scala:488-491). max_batch_size <= 1 disables coalescing;
    # batch_window_ms > 0 adds a flush timer (rarely needed: batches form
    # adaptively while the previous batch is in flight on the worker thread).
    max_batch_size: int = 128
    batch_window_ms: float = 0.0
    # -- resilience (see docs/resilience.md) --------------------------------
    # per-request deadline: a /queries.json answer is due within this many
    # seconds or the request is failed with 503 instead of hanging; <= 0
    # disables (NOT recommended: a wedged device call then blocks forever)
    request_timeout_s: float = 10.0
    # admission control: when this many queries are already waiting in the
    # micro-batch queue, new arrivals are shed with 503 + Retry-After
    # instead of growing the queue without bound; 0 = unbounded
    queue_high_water: int = 256
    shed_retry_after_s: float = 1.0  # Retry-After hint on load-shed 503s
    # oversized request bodies are rejected with 413 before JSON decode
    max_payload_bytes: int = 1 << 20
    # background HTTP (feedback + remote log) total timeout: a stalled
    # collector must not accumulate hung tasks forever
    http_timeout_s: float = 10.0
    # dispatch circuit breaker: this many consecutive watchdog trips (device
    # calls blowing their deadline) opens the circuit and sheds all traffic
    # for breaker_recovery_s before probing again
    breaker_threshold: int = 3
    breaker_recovery_s: float = 5.0

    def ssl_context(self):
        from predictionio_tpu.utils.tls import server_ssl_context

        return server_ssl_context(self.ssl_certfile, self.ssl_keyfile)


def _swallow_result(fut) -> None:
    """Done-callback for executor futures the watchdog may abandon: retrieve
    the late exception so the loop never logs 'exception was never
    retrieved' for a batch that was already failed and answered."""
    if not fut.cancelled():
        fut.exception()


class _QItem(NamedTuple):
    """One queued query: its payload, the caller's future, the request
    deadline, the ingress trace id (the contextvar does NOT survive the
    hop onto the dispatch thread — it rides here instead), and the
    enqueue time (queue-wait accounting)."""

    payload: Any
    fut: asyncio.Future
    deadline: Deadline
    trace_id: str | None
    t_submit: float


class _MicroBatcher:
    """Coalesces concurrent /queries.json requests into batched predicts.

    Requests enqueue (payload, future) pairs; a single dispatcher pulls
    everything pending (up to ``max_batch``) and hands the batch to a
    dedicated worker thread, which runs the full decode -> supplement ->
    predict_batch -> serve pipeline off the event loop. Batching is
    *adaptive*: while the worker is busy with batch n, new arrivals
    accumulate and become batch n+1 — a solo request dispatches immediately
    (no timer penalty), a concurrent burst converges to one device call per
    batch. An optional flush window can be configured but is 0 by default.
    """

    def __init__(
        self,
        server: "QueryServer",
        max_batch: int,
        window_s: float,
        max_inflight: int = 4,
        high_water: int = 0,
        shed_retry_after_s: float = 1.0,
    ):
        import concurrent.futures

        self._server = server
        self.max_batch = max(1, max_batch)
        self.window_s = max(0.0, window_s)
        self.high_water = max(0, high_water)
        self.shed_retry_after_s = shed_retry_after_s
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._closed = False
        self._max_fetch_workers = max(1, max_inflight)
        # dispatch runs on one thread (decode + device enqueue, fast);
        # fetches block on the transport and overlap on their own threads
        self._dispatch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-dispatch"
        )
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._max_fetch_workers, thread_name_prefix="pio-fetch"
        )
        self._inflight = asyncio.Semaphore(max(1, max_inflight))
        self._finish_tasks: set[asyncio.Task] = set()
        self._cancelled_tasks: list[asyncio.Task] = []
        self.batches_dispatched = 0
        self.queries_dispatched = 0
        self.watchdog_trips = 0  # batches failed for blowing their deadline
        self.shed_count = 0  # requests rejected by admission control

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    async def submit(self, payload: Any, deadline: Deadline | None = None) -> Any:
        """Enqueue one query payload; returns the encoded result body or
        raises the per-query error. Fails fast when the server is shutting
        down (never restarts the collect loop against shut-down pools) and
        sheds with ``LoadShedError`` when the queue is over high water."""
        if self._closed:
            raise ShuttingDownError()
        if self.high_water and self._queue.qsize() >= self.high_water:
            self.shed_count += 1
            self._server._m_shed.inc()
            raise LoadShedError(
                f"admission queue over high water "
                f"({self._queue.qsize()}/{self.high_water})",
                self.shed_retry_after_s,
            )
        if deadline is None:
            deadline = Deadline.never()
        fut = asyncio.get_running_loop().create_future()
        self._queue.put_nowait(
            _QItem(payload, fut, deadline, current_trace_id(), time.perf_counter())
        )
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())
        return await fut

    @staticmethod
    def _fail_batch(batch: list[_QItem], exc: BaseException) -> None:
        for item in batch:
            if not item.fut.done():
                item.fut.set_exception(exc)

    def _replace_dispatch_pool(self) -> None:
        """Abandon a dispatch thread stuck past its batch's deadline: the
        single dispatch thread is the serialization point for ALL traffic,
        so a wedged device call head-of-line-blocks every later batch
        unless we walk away from it. The old executor is shut down without
        cancelling the running call (it cannot be interrupted); its thread
        finishes (or hangs) in the background while a fresh pool serves
        new batches."""
        import concurrent.futures

        old = self._dispatch_pool
        self._dispatch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-dispatch"
        )
        old.shutdown(wait=False)

    def _replace_fetch_pool(self) -> None:
        """Same walk-away for a finalize stuck on the transport. Other
        in-flight finalizes on the old pool run to completion there."""
        import concurrent.futures

        old = self._fetch_pool
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._max_fetch_workers, thread_name_prefix="pio-fetch"
        )
        old.shutdown(wait=False)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            item = await self._queue.get()
            batch = [item]
            try:
                if self.window_s > 0:
                    await asyncio.sleep(self.window_s)
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self._queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                await self._inflight.acquire()  # bound batches in flight
            except asyncio.CancelledError:
                # shutdown while holding a collected-but-undispatched batch:
                # its clients must get a response, not an eternal await
                self._fail_batch(batch, ShuttingDownError())
                raise
            # requests that expired while queued are failed here, not
            # dispatched: device work for an answer nobody is waiting on
            # would only deepen an overload
            collect_t = time.perf_counter()
            live = []
            for item in batch:
                if item.fut.done():  # client gone / cancelled
                    # its probe slot (if it held one) can never be recorded
                    self._server.dispatch_breaker.release_probe()
                    continue
                if item.deadline.expired:
                    item.fut.set_exception(
                        DeadlineExceeded("query expired in admission queue")
                    )
                else:
                    live.append(item)
                    self._server._m_queue_wait.observe(
                        collect_t - item.t_submit
                    )
            if not live:
                self._inflight.release()
                continue
            batch = live
            batch_deadline = Deadline.min_of([it.deadline for it in batch])
            # dispatch under a watchdog. NOT wait_for(): cancelling an
            # executor future whose fn is already running blocks until the
            # fn returns — the exact hang the watchdog exists to escape.
            # asyncio.wait() times out without cancelling; the stuck call
            # is then abandoned and its pool replaced.
            dispatch_t0 = time.perf_counter()
            try:
                exec_fut = loop.run_in_executor(
                    self._dispatch_pool,
                    self._server._dispatch_query_batch,
                    [(it.payload, it.trace_id) for it in batch],
                )
                exec_fut.add_done_callback(_swallow_result)
                done, pending = await asyncio.wait(
                    [exec_fut], timeout=batch_deadline.remaining()
                )
            except asyncio.CancelledError:
                self._inflight.release()
                # shutdown mid-dispatch: this batch's clients must get a
                # response too (close()'s drain only covers queued items)
                self._fail_batch(batch, ShuttingDownError())
                raise  # close() must actually terminate the collect loop
            if pending:
                # watchdog trip: fail THIS batch, walk away from the stuck
                # dispatch thread, keep serving everyone else
                self._inflight.release()
                self.watchdog_trips += 1
                self._server._m_watchdog.inc()
                self._replace_dispatch_pool()
                self._server.dispatch_breaker.record_failure()
                self._fail_batch(
                    batch,
                    DeadlineExceeded("micro-batch dispatch: deadline exceeded"),
                )
                continue
            dispatch_s = time.perf_counter() - dispatch_t0
            self._server._m_dispatch.observe(dispatch_s)
            try:
                finalize = exec_fut.result()
            except BaseException as exc:
                self._inflight.release()
                self._server.dispatch_breaker.record_failure()
                for item in batch:
                    if not item.fut.done():
                        item.fut.set_exception(exc)
                continue
            self.batches_dispatched += 1
            self.queries_dispatched += len(batch)
            # finish asynchronously: the collect loop immediately forms and
            # dispatches the next batch while this one's fetch is in flight
            task = asyncio.ensure_future(
                self._finish(batch, finalize, batch_deadline, dispatch_s)
            )
            self._finish_tasks.add(task)
            task.add_done_callback(self._finish_tasks.discard)

    async def _finish(
        self,
        batch: list[_QItem],
        finalize,
        deadline: Deadline,
        dispatch_s: float = 0.0,
    ) -> None:
        loop = asyncio.get_running_loop()
        fetch_t0 = time.perf_counter()
        exec_fut = loop.run_in_executor(self._fetch_pool, finalize)
        exec_fut.add_done_callback(_swallow_result)
        try:
            done, pending = await asyncio.wait(
                [exec_fut], timeout=deadline.remaining()
            )
        except asyncio.CancelledError:
            self._inflight.release()
            # shutdown: resolve the batch's futures (handlers awaiting them
            # would otherwise hang for aiohttp's whole shutdown timeout)
            self._fail_batch(batch, ShuttingDownError())
            raise
        if pending:
            # fetch watchdog: same walk-away as dispatch (see _run); other
            # finalizes in flight on the old pool still run to completion
            self._inflight.release()
            self.watchdog_trips += 1
            self._server._m_watchdog.inc()
            self._replace_fetch_pool()
            self._server.dispatch_breaker.record_failure()
            self._fail_batch(
                batch, DeadlineExceeded("micro-batch fetch: deadline exceeded")
            )
            return
        fetch_s = time.perf_counter() - fetch_t0
        self._server._m_fetch.observe(fetch_s)
        # the fetch phase is where the host blocks on the device transport:
        # account it as stall time (see obs/jaxprof.py)
        self._server._m_stall.inc(fetch_s, where="micro-batch-fetch")
        try:
            outs = exec_fut.result()
        except BaseException as exc:
            # a finalize that raised wholesale is a dispatch-path failure
            # (per-query errors are isolated inside finalize and arrive as
            # entries in outs) — it must count against the breaker exactly
            # like a failed dispatch, not close a half-open circuit
            outs = [exc] * len(batch)
            self._server.dispatch_breaker.record_failure()
        else:
            self._server.dispatch_breaker.record_success()
        finally:
            self._inflight.release()
        done_t = time.perf_counter()
        for item, out in zip(batch, outs):
            # one `batch` span per query, carrying the wall/queue/device
            # split — the hop between the ingress span and any storage
            # spans the engine's serving components recorded
            self._server.tracer.record_span(
                "query.batch",
                kind="batch",
                duration_s=done_t - item.t_submit,
                trace_id=item.trace_id,
                status=type(out).__name__ if isinstance(out, BaseException) else "ok",
                batch_size=len(batch),
                queue_ms=round((fetch_t0 - dispatch_s - item.t_submit) * 1000, 3),
                dispatch_ms=round(dispatch_s * 1000, 3),
                fetch_ms=round(fetch_s * 1000, 3),
            )
            if item.fut.done():  # client gone / cancelled
                continue
            if isinstance(out, BaseException):
                item.fut.set_exception(out)
            else:
                item.fut.set_result(out)

    def close(self) -> None:
        self._closed = True  # new submits fail fast from here on
        if self._task is not None:
            self._task.cancel()
            self._cancelled_tasks.append(self._task)
            self._task = None
        for task in list(self._finish_tasks):
            task.cancel()
            self._cancelled_tasks.append(task)
        # fail everything still queued: enqueued-but-never-collected items
        # have handlers awaiting their futures (collected/dispatched batches
        # are resolved by the _run/_finish cancellation paths)
        exc = ShuttingDownError()
        while True:
            try:
                item = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if not item.fut.done():
                item.fut.set_exception(exc)
        self._dispatch_pool.shutdown(wait=False, cancel_futures=True)
        self._fetch_pool.shutdown(wait=False, cancel_futures=True)

    async def wait_closed(self) -> None:
        """Drain the cancellations issued by ``close()`` so shutdown leaves
        zero pending asyncio tasks behind."""
        tasks = [t for t in self._cancelled_tasks if not t.done()]
        self._cancelled_tasks.clear()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)


class QueryServer:
    def __init__(
        self,
        engine: Engine,
        engine_params: EngineParams,
        models: list[Any],
        manifest: EngineManifest,
        instance_id: str,
        storage: Storage | None = None,
        config: ServerConfig | None = None,
        plugin_context=None,
    ):
        from predictionio_tpu.workflow.server_plugins import (
            EngineServerPluginContext,
        )

        self.engine = engine
        self.engine_params = engine_params
        self.manifest = manifest
        self.instance_id = instance_id
        self.storage = storage or Storage.instance()
        self.config = config or ServerConfig()
        self.plugin_context = plugin_context or EngineServerPluginContext()
        _, _, algorithms, serving = engine.make_components(engine_params)
        # (algorithms, serving, models) live in ONE tuple swapped atomically:
        # the dispatch thread snapshots it in a single attribute read, so a
        # concurrent /reload can never pair new algorithms with old models
        # (attribute-by-attribute assignment allowed exactly that interleave)
        self._active: tuple[list[Any], Any, list[Any]] = (
            algorithms,
            serving,
            models,
        )
        self.start_time = _dt.datetime.now(tz=UTC)
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0
        # -- observability (docs/observability.md) --------------------------
        self.metrics = MetricsRegistry()
        self.tracer: Tracer = get_tracer()
        m = self.metrics
        self._m_requests = m.counter(
            "pio_requests_total",
            "HTTP requests served, by route and status",
            labelnames=("endpoint", "status"),
        )
        # ONE latency histogram backs both the legacy `/` status page and
        # /metrics — two independent ladders reported different p95s for
        # the same traffic and sent operators chasing phantom regressions
        self._m_latency = m.histogram(
            "pio_request_seconds",
            "HTTP request wall time, by route",
            labelnames=("endpoint",),
        )
        self._m_queue_wait = m.histogram(
            "pio_queue_wait_seconds",
            "time queries spend in the micro-batch admission queue",
        )
        self._m_dispatch = m.histogram(
            "pio_dispatch_seconds",
            "micro-batch dispatch phase (decode + device enqueue) wall time",
        )
        self._m_fetch = m.histogram(
            "pio_fetch_seconds",
            "micro-batch fetch phase (device->host transport + serve) wall time",
        )
        self._m_stall = m.counter(
            "pio_device_stall_seconds_total",
            "cumulative seconds spent blocked on device->host synchronization",
            labelnames=("where",),
        )
        self._m_shed = m.counter(
            "pio_load_shed_total",
            "requests rejected by admission control (503 + Retry-After)",
        )
        self._m_deadline = m.counter(
            "pio_deadline_exceeded_total",
            "requests failed for blowing their deadline (queued or in flight)",
        )
        self._m_watchdog = m.counter(
            "pio_watchdog_trips_total",
            "batches abandoned because a device call blew its deadline",
        )
        self._m_breaker_rejected = m.counter(
            "pio_breaker_rejections_total",
            "requests shed at the door because the dispatch circuit was open",
        )
        self._breaker_instruments = BreakerInstruments(m)
        # jit cache misses / XLA compile events become first-class metrics;
        # sampled at scrape time via the registry collector hook
        self.compile_watcher = CompileWatcher(m)
        m.register_collector(self.compile_watcher.sample)
        m.register_collector(self._breaker_instruments.collect)
        self._runner: web.AppRunner | None = None
        self._stop_event = asyncio.Event()
        # strong refs to fire-and-forget tasks (the loop keeps only weak ones)
        self._bg_tasks: set[asyncio.Task] = set()
        # ONE shared session with a total timeout for all background HTTP
        # (feedback + remote log): per-call bare ClientSessions with no
        # timeout accumulated hung tasks forever against a stalled collector
        self._http_session = None
        # consecutive watchdog trips (device calls blowing their deadline)
        # open this breaker; while open /queries.json sheds instantly with
        # 503 + Retry-After instead of feeding more work to a wedged device
        self.dispatch_breaker = self._breaker_instruments.watch(
            CircuitBreaker(
                name="dispatch",
                failure_threshold=self.config.breaker_threshold,
                recovery_timeout_s=self.config.breaker_recovery_s,
            )
        )
        self._reload_lock = asyncio.Lock()
        self._batcher = _MicroBatcher(
            self,
            max_batch=self.config.max_batch_size,
            window_s=self.config.batch_window_ms / 1000.0,
            high_water=self.config.queue_high_water,
            shed_retry_after_s=self.config.shed_retry_after_s,
        )
        # scrape-time gauges mirroring live batcher state (hot path pays 0)
        m.gauge(
            "pio_queue_depth", "queries waiting in the micro-batch queue"
        ).set_function(lambda: self._batcher.queue_depth)
        m.gauge(
            "pio_queue_high_water",
            "admission-control shed threshold (0 = unbounded)",
        ).set(self.config.queue_high_water)
        import concurrent.futures

        self._sniffer_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-sniffer"
        )

    # ---------------------------------------------------------------- routes
    async def handle_queries(self, request: web.Request) -> web.Response:
        """Trace + metrics envelope around the query path: accept or mint
        the request's trace id (echoed in the response), record the
        ingress span, and count/observe every status — including the
        shed/deadline 503s the resilience layer used to decide silently."""
        trace_id = request.headers.get(TRACE_HEADER) or mint_trace_id()
        token = set_trace_id(trace_id)
        status = 500
        t0 = time.perf_counter()
        try:
            with self.tracer.span(
                "http.query", kind="ingress", endpoint="/queries.json"
            ) as sp:
                resp = await self._handle_queries_inner(request)
                status = resp.status
                sp.tags["status"] = status
        finally:
            reset_trace_id(token)
            self._m_requests.inc(endpoint="/queries.json", status=str(status))
            self._m_latency.observe(
                time.perf_counter() - t0, endpoint="/queries.json"
            )
        resp.headers[TRACE_HEADER] = trace_id
        return resp

    async def _handle_queries_inner(self, request: web.Request) -> web.Response:
        if self.config.accesskey:
            supplied = request.query.get("accessKey") or request.headers.get(
                "Authorization", ""
            ).removeprefix("Bearer ").strip()
            if supplied != self.config.accesskey:
                return web.json_response({"message": "Invalid accessKey."}, status=401)
        t0 = time.perf_counter()
        if (
            self.config.max_payload_bytes
            and request.content_length is not None
            and request.content_length > self.config.max_payload_bytes
        ):
            return web.json_response(
                {
                    "message": (
                        f"query payload too large "
                        f"({request.content_length} > "
                        f"{self.config.max_payload_bytes} bytes)"
                    )
                },
                status=413,
            )
        try:
            payload = await request.json()
        except Exception as exc:
            return web.json_response({"message": str(exc)}, status=400)
        try:
            # a wedged device has tripped the dispatch breaker: shed at the
            # door with a Retry-After instead of queueing doomed work
            self.dispatch_breaker.allow()
        except CircuitOpenError as exc:
            self._m_breaker_rejected.inc()
            return self._unavailable(
                "serving temporarily unavailable (dispatch circuit open)",
                exc.retry_after_s,
            )
        deadline = Deadline.after(self.config.request_timeout_s)
        try:
            # the batcher runs decode -> supplement -> predict_batch -> serve
            # on its worker thread, so the event loop never blocks on device
            # or storage work and concurrent requests coalesce into one
            # batched device call; the deadline rides along and bounds every
            # stage (queue wait, dispatch, result fetch)
            body = await self._batcher.submit(payload, deadline)
        except LoadShedError as exc:
            # this request died before any dispatch could record against the
            # breaker: free its half-open probe slot (no-op when closed/open)
            # or an unresolved probe would wedge the circuit half-open
            self.dispatch_breaker.release_probe()
            return self._unavailable(str(exc), exc.retry_after_s)
        except DeadlineExceeded as exc:
            self.dispatch_breaker.release_probe()
            self._m_deadline.inc()
            logger.warning("query deadline exceeded: %s", exc)
            return self._unavailable(str(exc), self.config.shed_retry_after_s)
        except ShuttingDownError as exc:
            self.dispatch_breaker.release_probe()
            return self._unavailable(str(exc), self.config.shed_retry_after_s)
        except Exception as exc:
            logger.exception("query failed")
            if self.config.log_url:
                import traceback

                tb = "".join(traceback.format_exception(exc))
                msg = f"Query:\n{payload}\n\nStack Trace:\n{tb}\n\n"
                self._spawn_bg(self._remote_log(msg))
            return web.json_response({"message": str(exc)}, status=400)
        elapsed = time.perf_counter() - t0
        self.request_count += 1
        self.last_serving_sec = elapsed
        self.avg_serving_sec += (elapsed - self.avg_serving_sec) / self.request_count
        if self.config.feedback:
            self._spawn_bg(self._send_feedback(payload, body))
        return web.json_response(body)

    def _dispatch_query_batch(self, items: list[tuple[Any, str | None]]):
        """Dispatch-phase of one micro-batch (runs on the dispatch thread):
        decode and supplement each query, then *dispatch* every algorithm's
        device work via ``predict_batch_dispatch`` without blocking on
        results. Returns a finalize callable (run on a fetch thread) that
        blocks on the transport, serves, and encodes — so the dispatcher can
        start batch n+1 while batch n's results are in flight.

        ``items`` pairs each payload with its ingress trace id; the id is
        re-installed around the per-query stages (decode/supplement here,
        serve in finalize) so spans those stages record — a serving
        component fetching user features from storage, say — join the
        request's trace across the thread hop.

        Per-query failures are isolated: the failing slot gets its
        exception, batch mates answer normally. Finalize returns one entry
        per payload — an encoded result body or an exception."""
        # ONE read of the atomic tuple: an in-flight batch is immune to
        # /reload and always sees a consistent (algorithms, serving, models)
        algorithms, serving, models = self._active
        payloads = [p for p, _ in items]
        trace_ids = [t for _, t in items]
        n = len(payloads)
        outs: list[Any] = [None] * n
        queries: list[Any] = [None] * n
        supplemented: list[Any] = [None] * n
        valid: list[int] = []
        for i, payload in enumerate(payloads):
            token = set_trace_id(trace_ids[i])
            try:
                q = self.engine.decode_query(payload)
                queries[i] = q
                supplemented[i] = serving.supplement(q)
                valid.append(i)
            except Exception as exc:
                outs[i] = exc
            finally:
                reset_trace_id(token)
        sup = [supplemented[i] for i in valid]
        finalizers: list[Any] = []
        if valid:
            for algo, model in zip(algorithms, models):
                fin = None
                try:
                    fin = algo.predict_batch_dispatch(model, sup)
                except Exception:
                    logger.exception(
                        "predict_batch_dispatch failed; deferring to fetch"
                    )
                finalizers.append(fin)

        def finalize() -> list[Any]:
            if not valid:
                return outs
            preds_per_algo: list[list[Any]] = []
            for fin, (algo, model) in zip(finalizers, zip(algorithms, models)):
                try:
                    if fin is not None:
                        preds = list(fin())
                    else:
                        preds = list(algo.predict_batch(model, sup))
                    if len(preds) != len(sup):
                        raise RuntimeError(
                            f"predict_batch returned {len(preds)} results "
                            f"for {len(sup)} queries"
                        )
                except Exception:
                    # isolate failures: retry each query on the single path
                    # so one poisonous query can't fail the whole batch
                    logger.exception(
                        "batched predict failed; falling back to per-query"
                    )
                    preds = []
                    for s in sup:
                        try:
                            preds.append(algo.predict(model, s))
                        except Exception as exc:
                            logger.exception("query predict failed")
                            preds.append(exc)
                preds_per_algo.append(preds)
            sniffed: list[tuple[Any, Any]] = []
            for row, i in enumerate(valid):
                token = set_trace_id(trace_ids[i])
                try:
                    plist = [preds[row] for preds in preds_per_algo]
                    for p in plist:
                        if isinstance(p, BaseException):
                            raise p
                    result = serving.serve(queries[i], plist)
                    result = self.plugin_context.apply_output_blockers(
                        self.manifest.variant, queries[i], result
                    )
                    outs[i] = Engine.encode_result(result)
                    sniffed.append((queries[i], result))
                except Exception as exc:
                    outs[i] = exc
                finally:
                    reset_trace_id(token)
            if sniffed and self.plugin_context.output_sniffers:
                # observers are fire-and-forget on their own thread: a slow
                # or throwing sniffer must neither delay the batch's
                # responses nor overwrite a successful result
                self._sniffer_pool.submit(self._notify_sniffers, sniffed)
            return outs

        return finalize

    def _notify_sniffers(self, sniffed: list) -> None:
        for query, result in sniffed:
            try:
                self.plugin_context.notify_output_sniffers(
                    self.manifest.variant, query, result
                )
            except Exception:
                logger.exception("output sniffer failed")

    @staticmethod
    def _unavailable(message: str, retry_after_s: float) -> web.Response:
        """503 with a Retry-After hint — the contract load balancers and
        well-behaved clients need to back off instead of hammering."""
        return web.json_response(
            {"message": message},
            status=503,
            headers={"Retry-After": str(max(1, round(retry_after_s)))},
        )

    def _spawn_bg(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _http(self):
        """The shared background-HTTP session, created lazily on the running
        loop with a total timeout (config.http_timeout_s) and closed by
        ``stop()``: a stalled collector now costs one bounded task, not an
        ever-growing pile of hung ones."""
        import aiohttp

        if self._http_session is None or self._http_session.closed:
            self._http_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.config.http_timeout_s)
            )
        return self._http_session

    async def _remote_log(self, message: str) -> None:
        """Ship a serving error to the remote collector: POST body is
        ``log_prefix`` + JSON of {engineInstance, message}
        (ref ``CreateServer.remoteLog``, CreateServer.scala:423-434)."""
        body = self.config.log_prefix + json.dumps(
            {"engineInstance": self.instance_id, "message": message}
        )
        try:
            async with self._http().post(self.config.log_url, data=body):
                pass  # response body unused; context exit releases the conn
        except Exception:
            logger.error("Unable to send remote log")

    async def _send_feedback(self, query: Any, prediction: Any) -> None:
        """POST a `predict` event back to the event server
        (ref CreateServer.scala:500-570)."""
        url = self.config.event_server_url
        key = self.config.feedback_access_key
        if not url or not key:
            return
        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": self.manifest.engine_id,
            "properties": {"query": query, "prediction": prediction},
        }
        try:
            async with self._http().post(
                f"{url}/events.json", params={"accessKey": key}, json=event
            ):
                pass
        except Exception:
            logger.exception("feedback POST failed")

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "alive",
                "engineId": self.manifest.engine_id,
                "engineVersion": self.manifest.version,
                "engineVariant": self.manifest.variant,
                "engineFactory": self.manifest.engine_factory,
                "engineInstanceId": self.instance_id,
                "startTime": self.start_time.isoformat(),
                "requestCount": self.request_count,
                "avgServingSec": self.avg_serving_sec,
                "lastServingSec": self.last_serving_sec,
                "latency": self._latency_summary_ms(),
                "batching": {
                    "batches": self._batcher.batches_dispatched,
                    "queries": self._batcher.queries_dispatched,
                    "avgBatchSize": (
                        self._batcher.queries_dispatched
                        / max(1, self._batcher.batches_dispatched)
                    ),
                },
                "resilience": self._resilience_snapshot(),
            }
        )

    def _latency_summary_ms(self) -> dict[str, Any]:
        """Legacy status-page latency block, derived from the SAME obs
        histogram /metrics exports (one source of truth; keys kept from
        the pre-registry LatencyHistogram). Counts every /queries.json
        answer including resilience 503s — the distribution an operator
        staring at `/` should see under load."""
        s = self._m_latency.summary(endpoint="/queries.json")
        if s["count"] == 0:
            return {"count": 0}
        return {
            "count": s["count"],
            "mean_ms": 1000.0 * s["mean"],
            "p50_ms": 1000.0 * s["p50"],
            "p95_ms": 1000.0 * s["p95"],
            "p99_ms": 1000.0 * s["p99"],
            "max_ms": 1000.0 * s["max"],
        }

    def _resilience_snapshot(self) -> dict[str, Any]:
        b = self._batcher
        return {
            "queueDepth": b.queue_depth,
            "queueHighWater": b.high_water,
            "watchdogTrips": b.watchdog_trips,
            "loadShedCount": b.shed_count,
            "breakers": {"dispatch": self.dispatch_breaker.snapshot()},
        }

    async def handle_healthz(self, request: web.Request) -> web.Response:
        """Readiness (distinct from the `/` liveness/status page): a load
        balancer drains this replica while the dispatch circuit is open or
        the admission queue is at high water, instead of sending traffic
        that would be shed."""
        snap = self._resilience_snapshot()
        shedding = (
            snap["queueHighWater"] > 0
            and snap["queueDepth"] >= snap["queueHighWater"]
        )
        ready = (
            not self._batcher._closed
            and not shedding
            and snap["breakers"]["dispatch"]["state"] != OPEN
        )
        return web.json_response(
            {"ready": ready, **snap}, status=200 if ready else 503
        )

    async def handle_reload(self, request: web.Request) -> web.Response:
        """Swap in the latest COMPLETED instance (ref MasterActor reload).

        Serialized: two concurrent /reloads used to interleave their
        ``engine_params`` / ``_active`` / ``instance_id`` assignments and
        could leave the server announcing instance A while serving B's
        models. Under the lock, everything is loaded and warmed first and
        the three fields commit together only after that succeeds."""
        async with self._reload_lock:
            loop = asyncio.get_running_loop()
            latest = await loop.run_in_executor(
                None,
                lambda: self.storage.get_meta_data_engine_instances()
                .get_latest_completed(
                    self.manifest.engine_id,
                    self.manifest.version,
                    self.manifest.variant,
                ),
            )
            if latest is None:
                return web.json_response(
                    {"message": "no completed engine instance found"}, status=404
                )
            try:
                engine_params = self._engine_params_of(latest)
                models = await loop.run_in_executor(
                    None,
                    lambda: load_models_for_instance(
                        self.engine, engine_params, latest.id, storage=self.storage
                    ),
                )
                _, _, algorithms, serving = self.engine.make_components(
                    engine_params
                )
                # warm the NEW components before they take traffic (warmup
                # failures are non-fatal by the same contract as deploy-time
                # warmup: the first burst just pays its XLA compiles)
                await loop.run_in_executor(
                    None, self._warmup_components, algorithms, models
                )
            except Exception as exc:
                logger.exception("reload failed")
                return web.json_response({"message": str(exc)}, status=500)
            # commit: one consistent swap, nothing mutated on any failure path
            self.engine_params = engine_params
            self._active = (algorithms, serving, models)  # atomic swap
            self.instance_id = latest.id
        logger.info("reloaded engine instance %s", latest.id)
        return web.json_response(
            {"message": "Reload successful", "instanceId": latest.id}
        )

    def _engine_params_of(self, instance: EngineInstance) -> EngineParams:
        variant = {
            "datasource": {"params": json.loads(instance.data_source_params or "{}")},
            "preparator": {"params": json.loads(instance.preparator_params or "{}")},
            "algorithms": json.loads(instance.algorithms_params or "[]"),
            "serving": {"params": json.loads(instance.serving_params or "{}")},
        }
        return self.engine.engine_params_from_variant(variant)

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition: request latency histogram, queue
        depth, shed/deadline/watchdog counters, breaker state, jit
        recompile count — everything `pio top` and a Prometheus scrape
        need."""
        return metrics_response(self.metrics)

    async def handle_traces_recent(self, request: web.Request) -> web.Response:
        return traces_response(self.tracer, request)

    async def handle_stop(self, request: web.Request) -> web.Response:
        self._stop_event.set()
        return web.json_response({"message": "Stopping."})

    async def handle_plugins(self, request: web.Request) -> web.Response:
        return web.json_response(self.plugin_context.to_json_dict())

    # ------------------------------------------------------------------- app
    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_status),
                web.get("/healthz", self.handle_healthz),
                web.get("/metrics", self.handle_metrics),
                web.get("/traces/recent", self.handle_traces_recent),
                web.post("/queries.json", self.handle_queries),
                # POST is the reference's contract (CreateServer.scala:618-626);
                # GET kept as a browser convenience
                web.post("/reload", self.handle_reload),
                web.get("/reload", self.handle_reload),
                web.post("/stop", self.handle_stop),
                web.get("/stop", self.handle_stop),
                web.get("/plugins.json", self.handle_plugins),
            ]
        )

        async def _close_batcher(app: web.Application) -> None:
            # cancel the collect loop while its event loop is still alive
            # (otherwise the pending task leaks a "loop is closed" warning)
            self._batcher.close()
            await self._batcher.wait_closed()
            await self._close_background()

        app.on_cleanup.append(_close_batcher)
        return app

    async def _close_background(self) -> None:
        """Cancel fire-and-forget tasks and close the shared HTTP session —
        the 'zero hung asyncio tasks after shutdown' half of the resilience
        contract."""
        for task in list(self._bg_tasks):
            task.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        self._bg_tasks.clear()
        if self._http_session is not None and not self._http_session.closed:
            await self._http_session.close()
        self._http_session = None

    @property
    def algorithms(self) -> list[Any]:
        return self._active[0]

    @property
    def serving(self) -> Any:
        return self._active[1]

    @property
    def models(self) -> list[Any]:
        return self._active[2]

    def _warmup(self) -> None:
        """Pre-compile serving programs (pow2 batch buckets etc.) so the
        first traffic burst after deploy/reload pays no XLA compiles."""
        algorithms, _, models = self._active
        self._warmup_components(algorithms, models)

    def _warmup_components(self, algorithms: list[Any], models: list[Any]) -> None:
        for algo, model in zip(algorithms, models):
            try:
                algo.warmup_serving(model, self.config.max_batch_size)
            except Exception:
                logger.exception("serving warmup failed (continuing)")
        # baseline the compile watcher AFTER warmup: the compiles warmup
        # just paid for are intentional; only compiles past this point are
        # serving-time recompiles worth alarming on
        try:
            self.compile_watcher.sample()
        except Exception:
            logger.exception("compile watcher baseline failed (continuing)")

    async def start(self) -> None:
        await asyncio.get_running_loop().run_in_executor(None, self._warmup)
        retries = max(1, self.config.bind_retries)
        last_error: Exception | None = None
        for attempt in range(retries):
            # fresh runner+site per attempt: a TCPSite cannot be re-started
            # after a failed bind (it stays registered with the runner)
            self._runner = web.AppRunner(self.make_app())
            await self._runner.setup()
            site = web.TCPSite(
                self._runner,
                self.config.ip,
                self.config.port,
                ssl_context=self.config.ssl_context(),
            )
            try:
                await site.start()
                break
            except OSError as exc:  # bind retry (ref MasterActor x3)
                last_error = exc
                await self._runner.cleanup()
                self._runner = None
                logger.warning(
                    "bind %s:%d failed (attempt %d/%d): %s",
                    self.config.ip,
                    self.config.port,
                    attempt + 1,
                    retries,
                    exc,
                )
                if attempt + 1 < retries:
                    await asyncio.sleep(1.0)
        else:
            raise last_error  # type: ignore[misc]
        logger.info("engine server on %s:%d", self.config.ip, self.config.port)

    async def stop(self) -> None:
        self._batcher.close()
        await self._batcher.wait_closed()
        self._sniffer_pool.shutdown(wait=False, cancel_futures=True)
        await self._close_background()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def run_until_stopped(self) -> None:
        await self.start()
        await self._stop_event.wait()
        await self.stop()


def create_query_server(
    engine_dir: str,
    variant_path: str | None = None,
    storage: Storage | None = None,
    config: ServerConfig | None = None,
    instance_id: str | None = None,
) -> QueryServer:
    """Resolve the latest COMPLETED instance for the engine dir and build a
    server (ref commands/Engine.deploy :207-242)."""
    storage = storage or Storage.instance()
    manifest, engine = load_engine(engine_dir, variant_path)
    instances = storage.get_meta_data_engine_instances()
    if instance_id:
        instance = instances.get(instance_id)
        if instance is None:
            raise RuntimeError(f"engine instance {instance_id} not found")
    else:
        instance = instances.get_latest_completed(
            manifest.engine_id, manifest.version, manifest.variant
        )
        if instance is None:
            raise RuntimeError(
                f"no COMPLETED engine instance for {manifest.engine_id} "
                f"{manifest.version} {manifest.variant}; run train first"
            )
    engine_params = engine.engine_params_from_variant(manifest.variant_json)
    ctx = WorkflowContext(mode="serving", _storage=storage)
    models = load_models_for_instance(
        engine, engine_params, instance.id, ctx=ctx, storage=storage
    )
    return QueryServer(
        engine=engine,
        engine_params=engine_params,
        models=models,
        manifest=manifest,
        instance_id=instance.id,
        storage=storage,
        config=config,
    )


def run_query_server(
    engine_dir: str,
    variant_path: str | None = None,
    config: ServerConfig | None = None,
) -> None:
    server = create_query_server(engine_dir, variant_path, config=config)

    async def main():
        await server.run_until_stopped()

    asyncio.run(main())
