"""Engine-server plugin SPI.

Reference parity: ``core/.../workflow/EngineServerPlugin.scala:41`` +
``EngineServerPluginContext.scala:91`` — two kinds: output *blockers* run
synchronously and may rewrite or veto the prediction before it is returned;
output *sniffers* observe asynchronously. Mirrors the event server's input
plugin SPI.
"""

from __future__ import annotations

import abc
import logging
from typing import Any

logger = logging.getLogger(__name__)

OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


class EngineServerPlugin(abc.ABC):
    plugin_name: str = ""
    plugin_description: str = ""
    plugin_type: str = OUTPUT_SNIFFER

    def start(self, context: "EngineServerPluginContext") -> None:
        pass

    @abc.abstractmethod
    def process(
        self,
        engine_variant: str,
        query: Any,
        prediction: Any,
        context: "EngineServerPluginContext",
    ) -> Any:
        """Blockers return the (possibly rewritten) prediction or raise to
        veto; sniffers observe (return value ignored)."""

    def handle_rest(self, args: list[str]) -> Any:
        return {"message": "handleREST is not implemented."}


class EngineServerPluginContext:
    def __init__(
        self,
        plugins: list[EngineServerPlugin] | None = None,
        plugin_params: dict[str, dict] | None = None,
    ):
        self.plugin_params = plugin_params or {}
        self.output_blockers: dict[str, EngineServerPlugin] = {}
        self.output_sniffers: dict[str, EngineServerPlugin] = {}
        for p in plugins if plugins is not None else list(_REGISTRY):
            if p.plugin_type == OUTPUT_BLOCKER:
                self.output_blockers[p.plugin_name] = p
            else:
                self.output_sniffers[p.plugin_name] = p
            p.start(self)

    def apply_output_blockers(
        self, engine_variant: str, query: Any, prediction: Any
    ) -> Any:
        """Fold prediction through blockers (ref CreateServer.scala:572-576)."""
        for p in self.output_blockers.values():
            prediction = p.process(engine_variant, query, prediction, self)
        return prediction

    def notify_output_sniffers(
        self, engine_variant: str, query: Any, prediction: Any
    ) -> None:
        for p in self.output_sniffers.values():
            try:
                p.process(engine_variant, query, prediction, self)
            except Exception:
                logger.exception("output sniffer %s failed", p.plugin_name)

    def to_json_dict(self) -> dict[str, Any]:
        def describe(ps: dict[str, EngineServerPlugin]) -> dict[str, Any]:
            return {
                n: {
                    "name": p.plugin_name,
                    "description": p.plugin_description,
                    "class": type(p).__module__ + "." + type(p).__qualname__,
                }
                for n, p in ps.items()
            }

        return {
            "plugins": {
                "outputblockers": describe(self.output_blockers),
                "outputsniffers": describe(self.output_sniffers),
            }
        }


_REGISTRY: list[EngineServerPlugin] = []


def register_plugin(plugin: EngineServerPlugin) -> None:
    _REGISTRY.append(plugin)


def clear_plugins() -> None:
    _REGISTRY.clear()
