"""Mesh construction, sharding helpers and host->device ingest.

This package replaces the reference's Spark substrate (RDD partitioning,
spark-submit driver/executor topology, netty shuffle — see SURVEY.md section
2.1): parallelism is expressed as a `jax.sharding.Mesh` over TPU devices with
named axes, data is ingested host-side and laid out as sharded `jax.Array`s,
and all cross-device communication is XLA collectives over ICI/DCN.
"""

from predictionio_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    local_mesh,
)
from predictionio_tpu.parallel.ingest import (
    shard_columns,
    pad_to_multiple,
)

__all__ = ["MeshSpec", "make_mesh", "local_mesh", "shard_columns", "pad_to_multiple"]
