"""Device-mesh construction.

The standard axes, by convention across the framework:

  - ``data``  — batch/data parallelism (the reference's RDD-partition axis)
  - ``model`` — sharded factor/embedding tables (the reference delegated this
                to MLlib ALS block partitioning)

Single-chip and CPU test environments get a 1xN or Nx1 mesh transparently;
multi-host TPU slices get all addressable devices laid out by
``mesh_utils.create_device_mesh`` so the ``data`` axis rides ICI.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes; -1 means 'all remaining devices'."""

    axes: tuple[tuple[str, int], ...] = (("data", -1),)

    @staticmethod
    def parse(spec: str | None) -> "MeshSpec":
        """Parse "data=8,model=2" (engine.json / CLI surface)."""
        if not spec:
            return MeshSpec()
        axes = []
        for part in spec.split(","):
            name, _, size = part.partition("=")
            axes.append((name.strip(), int(size) if size else -1))
        return MeshSpec(tuple(axes))


def _resolve_sizes(axis_sizes: Sequence[int], n_devices: int) -> list[int]:
    sizes = list(axis_sizes)
    fixed = 1
    free = -1
    for i, s in enumerate(sizes):
        if s == -1:
            if free != -1:
                raise ValueError("at most one mesh axis may be -1")
            free = i
        else:
            fixed *= s
    if free != -1:
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes {fixed}"
            )
        sizes[free] = n_devices // fixed
    else:
        if fixed != n_devices:
            raise ValueError(
                f"mesh axes {sizes} require {fixed} devices, have {n_devices}"
            )
    return sizes


def make_mesh(
    spec: MeshSpec | str | None = None, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a named mesh. ``spec=None`` falls back to ``$PIO_MESH``
    (e.g. ``data=-1,model=2``), then to all devices on one ``data`` axis."""
    if spec is None:
        import os

        spec = os.environ.get("PIO_MESH") or None
    if isinstance(spec, str) or spec is None:
        spec = MeshSpec.parse(spec)
    devs = list(devices) if devices is not None else list(jax.devices())
    names = tuple(name for name, _ in spec.axes)
    sizes = _resolve_sizes([s for _, s in spec.axes], len(devs))
    if devices is not None:
        mesh_devices = np.asarray(devs).reshape(sizes)
    else:
        try:
            mesh_devices = mesh_utils.create_device_mesh(sizes, devices=devs)
        except (ValueError, AssertionError):
            mesh_devices = np.asarray(devs).reshape(sizes)
    return Mesh(mesh_devices, names)


def local_mesh() -> Mesh:
    """All local devices on one ``data`` axis — the dev/serving default."""
    return make_mesh(MeshSpec())


def data_sharding(mesh: Mesh, *, axis: str = "data") -> NamedSharding:
    """Rows sharded over the data axis, everything else replicated."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
