"""Host -> device ingest: columnar event blocks to sharded jax.Arrays.

Replaces the reference's storage-scan parallelism (JdbcRDD time-range
partitions, HBase region splits, ES shard splits — SURVEY.md section 2.1):
the host reads a columnar block once, pads it to a multiple of the data-axis
size (static shapes for XLA), and lays it out across the mesh with
``jax.device_put`` / ``make_array_from_process_local_data`` so each device
holds a contiguous row shard.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def pad_to_multiple(
    x: np.ndarray, multiple: int, pad_value: Any = 0
) -> tuple[np.ndarray, int]:
    """Pad axis 0 to a multiple; returns (padded, original_length)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width, constant_values=pad_value), n


def shard_columns(
    mesh: Mesh,
    columns: dict[str, np.ndarray],
    *,
    axis: str = "data",
    pad_values: dict[str, Any] | None = None,
    mask_name: str | None = None,
) -> tuple[dict[str, jax.Array], int]:
    """Shard equal-length host columns over the mesh's data axis.

    Single-process: rows are padded to a multiple of the axis size (pads at
    the TAIL, so masking by the returned original length works).

    Multi-process: each process passes its LOCAL rows; the processes
    coordinate one common per-process padded length (an allgather of local
    counts — uneven counts would otherwise make every process infer a
    different global shape and corrupt the first collective), and the
    result is a globally-sharded array via
    ``make_array_from_process_local_data`` with an explicit global shape.
    Pad rows then sit at the tail of each process's REGION — the middle of
    the global array — so masking by length is wrong there: pass
    ``mask_name`` to get a boolean validity column (sharded identically)
    under that key, which is correct in both modes.

    Returns ``(arrays, local_row_count)``.
    """
    pad_values = pad_values or {}
    axis_size = mesh.shape[axis]
    sharding = NamedSharding(mesh, PartitionSpec(axis))
    lengths = {col.shape[0] for col in columns.values()}
    if len(lengths) > 1:
        raise ValueError("all columns must have the same length")
    n_local = lengths.pop() if lengths else 0

    multi = jax.process_count() > 1
    if multi:
        from jax.experimental import multihost_utils

        counts = np.asarray(
            multihost_utils.process_allgather(np.asarray(n_local, np.int64))
        ).reshape(-1)
        per_len = int(-(-int(counts.max()) // axis_size) * axis_size)
        per_len = max(per_len, axis_size)
        global_rows = per_len * jax.process_count()
    else:
        per_len = n_local + ((-n_local) % axis_size)
        per_len = max(per_len, axis_size) if n_local else axis_size
        global_rows = per_len

    def put(local: np.ndarray) -> jax.Array:
        if multi:
            return jax.make_array_from_process_local_data(
                sharding, local, (global_rows, *local.shape[1:])
            )
        return jax.device_put(local, sharding)

    out: dict[str, jax.Array] = {}
    for name, col in columns.items():
        pad = per_len - col.shape[0]
        pad_width = [(0, pad)] + [(0, 0)] * (col.ndim - 1)
        out[name] = put(
            np.pad(col, pad_width, constant_values=pad_values.get(name, 0))
        )
    if mask_name is not None:
        mask = np.zeros((per_len,), bool)
        mask[:n_local] = True
        out[mask_name] = put(mask)
    return out, int(n_local)
