"""Host -> device ingest: columnar event blocks to sharded jax.Arrays.

Replaces the reference's storage-scan parallelism (JdbcRDD time-range
partitions, HBase region splits, ES shard splits — SURVEY.md section 2.1):
the host reads a columnar block once, pads it to a multiple of the data-axis
size (static shapes for XLA), and lays it out across the mesh with
``jax.device_put`` / ``make_array_from_process_local_data`` so each device
holds a contiguous row shard.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def pad_to_multiple(
    x: np.ndarray, multiple: int, pad_value: Any = 0
) -> tuple[np.ndarray, int]:
    """Pad axis 0 to a multiple; returns (padded, original_length)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width, constant_values=pad_value), n


def shard_columns(
    mesh: Mesh,
    columns: dict[str, np.ndarray],
    *,
    axis: str = "data",
    pad_values: dict[str, Any] | None = None,
) -> tuple[dict[str, jax.Array], int]:
    """Shard equal-length host columns over the mesh's data axis.

    Rows are padded to a multiple of the axis size; callers mask with the
    returned original length. In multi-process mode each process passes its
    local rows and the result is a globally-sharded array
    (``make_array_from_process_local_data``); single-process mode uses a
    plain sharded device_put.
    """
    pad_values = pad_values or {}
    axis_size = mesh.shape[axis]
    sharding = NamedSharding(mesh, PartitionSpec(axis))
    out: dict[str, jax.Array] = {}
    n_rows = None
    for name, col in columns.items():
        padded, n = pad_to_multiple(col, axis_size, pad_values.get(name, 0))
        if n_rows is None:
            n_rows = n
        elif n != n_rows:
            raise ValueError("all columns must have the same length")
        if jax.process_count() > 1:
            out[name] = jax.make_array_from_process_local_data(sharding, padded)
        else:
            out[name] = jax.device_put(padded, sharding)
    return out, int(n_rows or 0)
