"""Multi-host distributed runtime.

Replaces the reference's driver/executor split (``Runner.runOnSpark``
spark-submit + executor fleet, SURVEY.md section 2.1) with JAX's
single-controller-per-host model: the SAME CLI command runs once per TPU
host; ``jax.distributed.initialize`` joins them over DCN (coordinator
rendezvous), after which ``jax.devices()`` spans the slice and every jit
with sharded inputs runs SPMD with XLA collectives over ICI/DCN.

Environment contract (set by the launcher / scheduler):
  PIO_COORDINATOR        host:port of process 0 (alias: JAX_COORDINATOR_ADDRESS)
  PIO_NUM_PROCESSES      total host count
  PIO_PROCESS_ID         this host's index
Absent -> single-process mode (no-op), so every code path works unchanged
on one host.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_initialized = False


def maybe_initialize_distributed() -> bool:
    """Join the multi-host job when the env contract is present. Returns
    True when running distributed."""
    global _initialized
    if _initialized:
        return True
    coordinator = os.environ.get("PIO_COORDINATOR") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if not coordinator:
        return False
    num_processes = int(os.environ.get("PIO_NUM_PROCESSES", "1"))
    process_id = int(os.environ.get("PIO_PROCESS_ID", "0"))
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "joined distributed job: process %d/%d via %s",
        process_id,
        num_processes,
        coordinator,
    )
    return True


def process_info() -> dict:
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0
