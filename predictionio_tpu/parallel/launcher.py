"""Multi-host job launcher — the framework *creates* the distributed job.

Reference parity: ``tools/.../Runner.scala:185-334`` (``Runner.runOnSpark``)
assembles a spark-submit command line, manages the child process, and cleans
up on exit. The TPU-native equivalent launches ONE worker process per host
with the ``PIO_COORDINATOR``/``PIO_NUM_PROCESSES``/``PIO_PROCESS_ID``
contract consumed by ``parallel.distributed.maybe_initialize_distributed``,
supervises the fleet, propagates the first failure (terminating the
stragglers, as Runner's shutdown hook kills its spark-submit child), and
reaps everything on exit.

Two placement modes:
  - local (``num_hosts``): all workers on this machine — how single-host
    multi-process jobs and the CI rendezvous test run, and the degenerate
    form of a TPU pod slice with one process per chip group.
  - remote (``hosts=[h1, h2, ...]``): one worker per host via ``ssh`` with
    the env contract inlined — the moral equivalent of Runner's cluster
    submission (deploy tooling like GKE/xmanager replaces this in real
    fleets; the env contract is identical either way).
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


def pick_free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclass
class LaunchResult:
    returncodes: list[int]

    @property
    def ok(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)


@dataclass
class MultiHostLauncher:
    """Spawn + supervise one worker process per host.

    ``command`` is the worker argv (e.g. ``[sys.executable, "-m",
    "predictionio_tpu.tools.cli", "train", ...]``). Each worker gets the
    coordinator env triplet; everything else is inherited.
    """

    command: list[str]
    num_hosts: int = 1
    hosts: list[str] | None = None  # remote mode when set
    coordinator_host: str | None = None
    coordinator_port: int | None = None
    env_extra: dict[str, str] = field(default_factory=dict)
    stream_logs: bool = True
    _procs: list[subprocess.Popen] = field(default_factory=list, init=False)

    def _worker_env(self, process_id: int, coordinator: str) -> dict[str, str]:
        env = dict(os.environ)
        env.update(self.env_extra)
        env["PIO_COORDINATOR"] = coordinator
        env["PIO_NUM_PROCESSES"] = str(self.n_processes)
        env["PIO_PROCESS_ID"] = str(process_id)
        return env

    @property
    def n_processes(self) -> int:
        return len(self.hosts) if self.hosts else self.num_hosts

    @property
    def _stdout_target(self) -> int:
        # stream_logs=False must NOT leave a PIPE nobody drains: a worker
        # writing past the ~64KB OS pipe buffer would block in write() and
        # the fleet would hang forever in _supervise
        return subprocess.PIPE if self.stream_logs else subprocess.DEVNULL

    def _spawn_local(self, process_id: int, coordinator: str) -> subprocess.Popen:
        return subprocess.Popen(
            self.command,
            env=self._worker_env(process_id, coordinator),
            stdout=self._stdout_target,
            stderr=subprocess.STDOUT,
            start_new_session=True,  # isolate signals: we terminate explicitly
        )

    def _spawn_remote(
        self, host: str, process_id: int, coordinator: str
    ) -> subprocess.Popen:
        # env contract inlined into the remote command; cwd mirrored so
        # engine dirs resolve the same way on every host
        assignments = " ".join(
            f"{k}={shlex.quote(v)}"
            for k, v in {
                **self.env_extra,
                "PIO_COORDINATOR": coordinator,
                "PIO_NUM_PROCESSES": str(self.n_processes),
                "PIO_PROCESS_ID": str(process_id),
            }.items()
        )
        remote = f"cd {shlex.quote(os.getcwd())} && env {assignments} " + " ".join(
            shlex.quote(c) for c in self.command
        )
        return subprocess.Popen(
            ["ssh", "-o", "BatchMode=yes", host, remote],
            stdout=self._stdout_target,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )

    def _pump(self, idx: int, proc: subprocess.Popen) -> None:
        """Prefix-stream a worker's output (ref Runner inherits stdio; a
        fleet needs per-process attribution)."""
        assert proc.stdout is not None
        for line in proc.stdout:
            sys.stderr.write(f"[host {idx}] {line.decode(errors='replace')}")

    def run(self, poll_interval: float = 0.2) -> LaunchResult:
        """Launch the fleet and block until every worker exits. The first
        nonzero exit terminates the remaining workers (fail-fast, matching
        a collective job's semantics: a lost process wedges the others at
        the next collective anyway)."""
        n = self.n_processes
        host = self.coordinator_host or (
            self.hosts[0] if self.hosts else "127.0.0.1"
        )
        # hosts entries are ssh targets and may carry a user prefix
        # ("ubuntu@10.0.0.1") — the JAX coordinator address must be a bare
        # host:port or every worker's rendezvous fails on the malformed URL
        host = host.rsplit("@", 1)[-1]
        # NOTE: with a port chosen here, remote mode assumes the port is
        # also free on the coordinator HOST (we can only probe locally);
        # pass coordinator_port explicitly to pin a known-free one
        port = self.coordinator_port or pick_free_port()
        coordinator = f"{host}:{port}"
        logger.info("launching %d workers; coordinator %s", n, coordinator)
        pumps = []
        try:
            for pid in range(n):
                if self.hosts:
                    proc = self._spawn_remote(self.hosts[pid], pid, coordinator)
                else:
                    proc = self._spawn_local(pid, coordinator)
                self._procs.append(proc)
                if self.stream_logs:
                    t = threading.Thread(
                        target=self._pump, args=(pid, proc), daemon=True
                    )
                    t.start()
                    pumps.append(t)
            return self._supervise(poll_interval, pumps)
        finally:
            self.terminate()

    def _supervise(self, poll_interval: float, pumps: list) -> LaunchResult:
        procs = self._procs
        while True:
            states = [p.poll() for p in procs]
            failed = [rc for rc in states if rc not in (None, 0)]
            if failed:
                logger.error(
                    "worker failed (rc=%d); terminating remaining workers",
                    failed[0],
                )
                self.terminate()
                break
            if all(rc is not None for rc in states):
                break
            time.sleep(poll_interval)
        for p in procs:
            p.wait()
        for t in pumps:
            t.join(timeout=2.0)
        return LaunchResult([p.returncode for p in procs])

    def terminate(self, grace_s: float = 5.0) -> None:
        """SIGTERM every live worker, escalate to SIGKILL after ``grace_s``
        (ref Runner's shutdown-hook ``kill`` of its spark-submit child)."""
        live = [p for p in self._procs if p.poll() is None]
        for p in live:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.monotonic() + grace_s
        for p in live:
            remaining = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.1, remaining))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()


def launch_cli_multihost(
    cli_args: list[str],
    num_hosts: int,
    hosts: list[str] | None = None,
    env_extra: dict[str, str] | None = None,
) -> int:
    """Re-exec this framework's CLI once per host (the ``pio train
    --num-hosts N`` path). Returns an exit code: 0 iff every worker
    succeeded."""
    launcher = MultiHostLauncher(
        command=[sys.executable, "-m", "predictionio_tpu.tools.cli", *cli_args],
        num_hosts=num_hosts,
        hosts=hosts,
        env_extra=env_extra or {},
    )
    result = launcher.run()
    if not result.ok:
        logger.error("multi-host launch failed: rcs=%s", result.returncodes)
        return 1
    return 0
