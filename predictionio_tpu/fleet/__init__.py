"""Serving fleet: N supervised QueryServer replicas behind one gateway.

The single-process QueryServer is both the scaling ceiling and the
single point of failure; this package makes replica count a deployment
knob (``pio deploy --fleet N``) instead of a rewrite:

- :mod:`.supervisor` — spawns and watches the worker processes,
  restarting crashes with exponential backoff and a crash-loop budget;
- :mod:`.gateway` — routes queries (least-loaded + consistent-hash
  tie-break), ejects/readmits replicas from ``/healthz`` probes and
  per-replica circuit breakers, retries idempotent queries once on a
  different replica, and drains gracefully on SIGTERM;
- :mod:`.federation` — merges the replicas' Prometheus scrapes into the
  gateway's ``/metrics`` (the ``pio top --fleet`` endpoint);
- :mod:`.autoscaler` — SLO-driven elasticity: a control loop that reads
  the telemetry ring (fleet burn rates, queue-depth/inflight/shed
  history) and resizes the fleet through the supervisor and the
  gateway's membership funnel, with heterogeneous ``cpu-fallback``
  overflow replicas (``pio deploy --fleet N --autoscale``);
- :mod:`.launch` — the ``pio deploy --fleet N`` glue that runs
  supervisor + gateway (+ autoscaler) in one process.

Replicas coordinate ONLY through the model registry: its rollout state
carries a monotonic ``state_generation`` every worker polls, so a
promote/rollback issued through any replica (or the gateway) propagates
fleet-wide and flushes each per-process result cache. See
``docs/fleet.md``.
"""

from predictionio_tpu.fleet.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    ScalingPolicy,
)
from predictionio_tpu.fleet.federation import federate_metrics
from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig, Replica
from predictionio_tpu.fleet.supervisor import (
    REPLICA_CLASS_CPU,
    REPLICA_CLASS_DEVICE,
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "Gateway",
    "GatewayConfig",
    "REPLICA_CLASS_CPU",
    "REPLICA_CLASS_DEVICE",
    "Replica",
    "ScalingPolicy",
    "Supervisor",
    "SupervisorConfig",
    "WorkerSpec",
    "federate_metrics",
]
