"""Prometheus text federation: N replica scrapes -> one fleet exposition.

The gateway's ``/metrics`` is the fleet's single pane: counters are
summed across replicas, histogram series merge by adding per-``le``
cumulative bucket counts (every replica shares the fixed ladder from
``obs.metrics.DEFAULT_BUCKETS``, so bucket-wise addition is exact), and
``_sum``/``_count`` add like any counter. Gauges are summed too — right
for additive gauges (queue depth, in-flight), documented as
sum-of-replicas for the rest (``docs/fleet.md``); per-replica truth
stays one scrape away on the replica's own endpoint.

OpenMetrics tolerance: a replica scraped with ``?exemplars=1`` decorates
bucket lines with exemplar clauses (``… # {trace_id="…"} v``) and ends
with ``# EOF``. The merge VALUE math strips both via the same ``" # "``
split ``pio top`` uses (the sample still sums exactly); with
``exemplars=True`` the clauses are additionally *carried* onto the
merged output — last replica wins per series, which is the same
last-writer-wins the per-process histogram applies per bucket — so a
federated p99 exemplar still names a concrete trace id that the
gateway's ``/traces/recent?trace_id=`` assembles into a cross-tier
waterfall.

Built on the same stdlib parser ``pio top`` uses, so whatever a replica
can expose, the federated view can carry.
"""

from __future__ import annotations

import re

# _LABEL_RE/_unescape shared with the parser so exemplar-clause keys can
# never diverge from the merged-series keys parse_prometheus produces
from predictionio_tpu.tools.top import (
    _LABEL_RE,
    _parse_value,
    _unescape,
    parse_prometheus,
)

_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)\s*$")
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_SAMPLE_NAME_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s")


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _series_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _sample_sort_key(item):
    """Stable exposition order inside one metric: label sets sorted
    lexically, except the histogram ``le`` label which sorts numerically
    so bucket lines stay in ladder order."""
    labels = dict(item[0])
    le = labels.pop("le", None)
    return (
        sorted(labels.items()),
        _parse_value(le) if le is not None else float("-inf"),
    )


def _collect_exemplars(
    text: str,
) -> dict[tuple[str, tuple[tuple[str, str], ...]], str]:
    """``(series_name, label_key) -> exemplar clause`` for every sample
    line carrying one (`` # {…} v`` after the value). The clause is kept
    verbatim for re-attachment to the merged line."""
    out: dict[tuple[str, tuple[tuple[str, str], ...]], str] = {}
    for line in text.splitlines():
        if line.startswith("#") or " # " not in line:
            continue
        sample, clause = line.split(" # ", 1)
        m = _SAMPLE_NAME_RE.match(sample.strip() + " ")
        if not m:
            continue
        labels = {
            k: _unescape(v) for k, v in _LABEL_RE.findall(m.group(2) or "")
        }
        out[(m.group(1), _series_key(labels))] = clause.strip()
    return out


def federate_metrics(texts: list[str], exemplars: bool = False) -> str:
    """Merge N Prometheus/OpenMetrics text expositions into one.

    Identical ``(name, labels)`` series have their values summed; HELP and
    TYPE lines are carried from the first exposition that declares them.
    Input order is the replica order — series unique to one replica pass
    through unchanged. Exemplar clauses and ``# EOF`` in the inputs never
    corrupt the sums (stripped before value parsing); with
    ``exemplars=True`` the clauses are re-attached to the merged lines
    (last input wins per series) and the output ends with ``# EOF`` —
    serve that variant only to scrapers that negotiated OpenMetrics.
    """
    merged: dict[str, dict[tuple[tuple[str, str], ...], float]] = {}
    exemplar_clauses: dict[tuple[str, tuple[tuple[str, str], ...]], str] = {}
    types: dict[str, str] = {}
    helps: dict[str, str] = {}
    order: list[str] = []
    for text in texts:
        for line in text.splitlines():
            m = _TYPE_RE.match(line)
            if m:
                types.setdefault(m.group(1), m.group(2))
                continue
            m = _HELP_RE.match(line)
            if m:
                helps.setdefault(m.group(1), m.group(2))
        for name, samples in parse_prometheus(text).items():
            series = merged.setdefault(name, {})
            if name not in order:
                order.append(name)
            for labels, value in samples:
                key = _series_key(labels)
                series[key] = series.get(key, 0.0) + value
        if exemplars:
            exemplar_clauses.update(_collect_exemplars(text))
    lines: list[str] = []
    for name in sorted(order):
        base = _base_metric_name(name, types)
        if base in helps and name == _first_series_name(base, order):
            lines.append(f"# HELP {base} {helps[base]}")
        if base in types and name == _first_series_name(base, order):
            lines.append(f"# TYPE {base} {types[base]}")
        for key, value in sorted(merged[name].items(), key=_sample_sort_key):
            label_str = ""
            if key:
                inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
                label_str = "{" + inner + "}"
            clause = exemplar_clauses.get((name, key)) if exemplars else None
            suffix = f" # {clause}" if clause else ""
            lines.append(f"{name}{label_str} {_format_value(value)}{suffix}")
    if exemplars:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _base_metric_name(series_name: str, types: dict[str, str]) -> str:
    """``pio_x_seconds_bucket`` -> ``pio_x_seconds`` when the base is a
    declared histogram; otherwise the series name is the metric name."""
    for suffix in ("_bucket", "_sum", "_count"):
        if series_name.endswith(suffix):
            base = series_name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base
    return series_name


def _first_series_name(base: str, order: list[str]) -> str:
    """The lexically-first series name of a metric family — HELP/TYPE are
    emitted exactly once, ahead of that series."""
    candidates = [
        n
        for n in order
        if n == base or n in (f"{base}_bucket", f"{base}_sum", f"{base}_count")
    ]
    return min(candidates) if candidates else base


__all__ = ["federate_metrics"]
