"""Worker stderr/stdout capture: bounded rotating tail files per replica.

Before this module the supervisor's workers inherited the parent's fds —
a crashed replica left exactly zero log evidence, and the incident
bundle that matters most (the crash) had nothing to embed. The logbook
gives every worker a stable log file the supervisor can tail after the
process is gone:

- :meth:`WorkerLogBook.open_for` returns a binary append handle for the
  worker's ``<name>.log``; rotation happens *at open time* (a respawned
  worker whose log outgrew ``max_bytes`` shifts it to ``<name>.log.1``
  first) because the file is owned by the child's fd while it runs —
  truncating under a live writer would interleave garbage.
- :func:`spawn_with_log` is the ``subprocess.Popen`` wrapper the fleet
  launcher uses: open, spawn with stdout+stderr pointed at the log,
  close the parent's copy (the child holds its own dup), return the
  proc.
- :meth:`WorkerLogBook.tail` reads the last ``max_bytes`` of the
  current log (reaching into ``.log.1`` when the current file is
  shorter than asked) — the excerpt incident bundles capture and
  ``pio incidents show`` prints.

Bounded by construction: at most ``max_bytes`` per generation and two
generations per worker, however long the fleet runs. Stdlib-only.
"""

from __future__ import annotations

import os
import subprocess
from typing import IO, Any

DEFAULT_MAX_BYTES = 256 * 1024


class WorkerLogBook:
    def __init__(self, dir_path: str, max_bytes: int = DEFAULT_MAX_BYTES):
        self.dir = dir_path
        self.max_bytes = int(max_bytes)
        os.makedirs(self.dir, exist_ok=True)

    def path(self, name: str) -> str:
        return os.path.join(self.dir, f"{name}.log")

    def rotated_path(self, name: str) -> str:
        return self.path(name) + ".1"

    def open_for(self, name: str) -> IO[bytes]:
        """Append handle for the worker's log, rotating first when the
        previous generation outgrew the budget."""
        path = self.path(name)
        try:
            if os.path.getsize(path) > self.max_bytes:
                os.replace(path, self.rotated_path(name))
        except OSError:
            pass  # no previous log: nothing to rotate
        return open(path, "ab")

    def tail(self, name: str, max_bytes: int = 8192) -> str:
        """Last ``max_bytes`` of the worker's output, rotation-aware:
        when the live log is shorter than asked, the gap is filled from
        the previous generation (a worker that crashed right after a
        rotation still shows its dying words)."""
        max_bytes = max(0, int(max_bytes))
        chunks: list[bytes] = []
        remaining = max_bytes
        for path in (self.path(name), self.rotated_path(name)):
            if remaining <= 0:
                break
            data = _tail_bytes(path, remaining)
            if data:
                chunks.insert(0, data)
                remaining -= len(data)
        return b"".join(chunks).decode("utf-8", errors="replace")


def _tail_bytes(path: str, n: int) -> bytes:
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - n))
            return fh.read()
    except OSError:
        return b""


def spawn_with_log(
    argv: list[str],
    logbook: WorkerLogBook,
    name: str,
    **popen_kw: Any,
) -> subprocess.Popen:
    """Spawn a worker with stdout+stderr captured into its logbook file.
    The parent's handle is closed right after the spawn — the child owns
    a dup, so the parent never leaks an fd per restart."""
    fh = logbook.open_for(name)
    try:
        return subprocess.Popen(
            argv, stdout=fh, stderr=subprocess.STDOUT, **popen_kw
        )
    finally:
        fh.close()


__all__ = ["WorkerLogBook", "spawn_with_log", "DEFAULT_MAX_BYTES"]
