"""Pluggable host runtime: where the fleet's worker processes live.

Every robustness property the fleet earned through PR 9-15 — zero-5xx
restarts, crash ladders, incident bundles, autoscaling — silently
assumed one machine: the supervisor ``Popen``ed workers next to itself.
This module breaks that assumption with a small driver interface
(``spawn`` / ``signal`` / ``poll`` / ``fetch_log_tail`` / ``probe``) and
a declared host inventory, so the same supervisor policy places workers
across N boxes:

- :class:`LocalHostDriver` — the original ``subprocess.Popen`` path,
  refactored to be *one driver among several* instead of the hard-wired
  default. ``--hosts`` unset collapses to exactly this, byte-for-byte.
- :class:`SshHostDriver` — stdlib subprocess-over-``ssh``. The local ssh
  client *is* the process handle: its stdout mirrors the remote worker's
  (so the logbook keeps capturing crash evidence with zero new
  machinery) and its exit status mirrors the remote exit status.
- :class:`ContainerHostDriver` — docker/podman CLI. One attached
  ``<engine> run`` client per worker; signals go through
  ``<engine> kill -s`` so the *container's* pid 1 gets them, not the
  attached client.
- :class:`FakeHostDriver` — real local processes grouped under fake
  host names, with a host-level kill switch. This is the chaos lever:
  ``kill_host()`` SIGKILLs every resident process *and* fails the
  host's liveness probe from then on, which is exactly what a kernel
  panic looks like from the supervisor's chair. CI drives the two-host
  survive-host-death gate through it without needing a second machine.

Inventory syntax (``--hosts``)::

    --hosts local:2,ssh@node1:4,container@pio-worker:2,fake@b:2

i.e. comma-separated ``[driver@]host:slots``; a bare ``host:slots``
means the local driver. Slots bound placement — the supervisor's
host-aware spawn path refuses to overfill a box.

Blocking by design: drivers shell out (ssh handshakes, docker starts).
The supervisor runs ``tick()`` on an executor thread, never the serving
event loop — the same rule the autoscaler and incident captures follow.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
import signal as _signal
import subprocess
import time
from typing import IO, Any, Callable

from predictionio_tpu.fleet.worklog import WorkerLogBook

logger = logging.getLogger(__name__)

DRIVER_LOCAL = "local"
DRIVER_SSH = "ssh"
DRIVER_CONTAINER = "container"
DRIVER_FAKE = "fake"

_KNOWN_DRIVERS = (DRIVER_LOCAL, DRIVER_SSH, DRIVER_CONTAINER, DRIVER_FAKE)


@dataclasses.dataclass(frozen=True)
class HostSpec:
    """One box in the fleet inventory: a stable name (metric label,
    placement identity), how many worker slots it offers, which driver
    reaches it, and the address the gateway connects to its workers on
    (loopback for local/fake/container-with-host-network, the ssh target
    host otherwise)."""

    name: str
    slots: int
    driver: str = DRIVER_LOCAL
    address: str = ""  # ssh target (user@host) or container image
    connect_ip: str = "127.0.0.1"


def parse_hosts(spec: str) -> list[HostSpec]:
    """``[driver@]host:slots`` comma list -> inventory. Raises
    ``ValueError`` with an operator-grade message on malformed entries,
    duplicate names, unknown drivers, or non-positive slots."""
    hosts: list[HostSpec] = []
    seen: set[str] = set()
    for raw in (p.strip() for p in spec.split(",")):
        if not raw:
            continue
        head, sep, slots_s = raw.rpartition(":")
        if not sep or not head:
            raise ValueError(
                f"--hosts entry {raw!r}: expected [driver@]host:slots "
                "(e.g. local:2 or ssh@node1:4)"
            )
        try:
            slots = int(slots_s)
        except ValueError:
            raise ValueError(
                f"--hosts entry {raw!r}: slots must be an integer"
            ) from None
        if slots <= 0:
            raise ValueError(f"--hosts entry {raw!r}: slots must be >= 1")
        driver, sep, host = head.partition("@")
        if not sep:
            driver, host = DRIVER_LOCAL, head
        if driver not in _KNOWN_DRIVERS:
            raise ValueError(
                f"--hosts entry {raw!r}: unknown driver {driver!r} "
                f"(known: {', '.join(_KNOWN_DRIVERS)})"
            )
        if not host:
            raise ValueError(f"--hosts entry {raw!r}: empty host name")
        name = host
        address = ""
        connect_ip = "127.0.0.1"
        if driver == DRIVER_SSH:
            address = host
            # "user@host" ssh targets keep the user out of the dial addr
            name = host.rpartition("@")[2]
            connect_ip = name
        elif driver == DRIVER_CONTAINER:
            # the entry names the image; the host *name* is the image too
            # (one logical box per image entry), reachable on loopback via
            # --network host
            address = host
        if name in seen:
            raise ValueError(f"--hosts: duplicate host name {name!r}")
        seen.add(name)
        hosts.append(
            HostSpec(
                name=name,
                slots=slots,
                driver=driver,
                address=address,
                connect_ip=connect_ip,
            )
        )
    if not hosts:
        raise ValueError("--hosts: empty inventory")
    return hosts


class HostDriver:
    """The driver contract. All methods may block (subprocess waits, ssh
    handshakes) — callers run them on executor threads."""

    kind = "abstract"

    def spawn(
        self,
        host: HostSpec,
        name: str,
        argv: list[str],
        env: dict[str, str] | None = None,
    ) -> Any:  # ProcessHandle
        raise NotImplementedError

    def signal(self, host: HostSpec, name: str, handle: Any, sig: int) -> None:
        """Deliver ``sig`` to the worker. The default reaches through the
        local handle (Popen.send_signal); remote drivers override to
        signal the far side."""
        try:
            handle.send_signal(sig)
        except (OSError, ValueError):
            pass

    def poll(self, handle: Any) -> int | None:
        return handle.poll()

    def fetch_log_tail(
        self, host: HostSpec, name: str, max_bytes: int = 8192
    ) -> str:
        """Last bytes of the worker's captured output — the crash and
        host-death evidence incident bundles embed."""
        return ""

    def probe(self, host: HostSpec) -> bool:
        """Host-level liveness: can this driver still reach the box at
        all? (Distinct from per-worker exits: one worker dying is a
        crash; the probe failing is a *host death* and every resident
        worker is gone with it.)"""
        return True


class LocalHostDriver(HostDriver):
    """The original fleet spawn path (``worklog.spawn_with_log``) as a
    driver. The machine running the supervisor is by definition alive,
    so the probe never fails."""

    kind = DRIVER_LOCAL

    def __init__(self, logbook: WorkerLogBook | None = None):
        self.logbook = logbook

    def _open_log(self, name: str) -> IO[bytes] | None:
        return None if self.logbook is None else self.logbook.open_for(name)

    def spawn(
        self,
        host: HostSpec,
        name: str,
        argv: list[str],
        env: dict[str, str] | None = None,
    ) -> Any:
        fh = self._open_log(name)
        kw: dict[str, Any] = {}
        if env is not None:
            kw["env"] = env
        if fh is not None:
            kw["stdout"] = fh
            kw["stderr"] = subprocess.STDOUT
        try:
            return subprocess.Popen(argv, **kw)
        finally:
            if fh is not None:
                fh.close()

    def fetch_log_tail(
        self, host: HostSpec, name: str, max_bytes: int = 8192
    ) -> str:
        if self.logbook is None:
            return ""
        return self.logbook.tail(name, max_bytes)


class SshHostDriver(HostDriver):
    """Workers on a remote box over plain ``ssh`` (stdlib subprocess, no
    agent/library deps). The local ssh client is the handle: its stdout
    carries the remote worker's output into the logbook, its exit status
    mirrors the remote one, and killing it hangs up the session (sshd
    HUPs the remote process group). TERM/KILL are *also* delivered
    remotely via ``ssh <host> pkill`` keyed on the worker name, because
    a hangup alone races the remote drain."""

    kind = DRIVER_SSH

    def __init__(
        self,
        logbook: WorkerLogBook | None = None,
        ssh_argv: tuple[str, ...] = ("ssh", "-o", "BatchMode=yes"),
        probe_timeout_s: float = 5.0,
    ):
        self.logbook = logbook
        self.ssh_argv = list(ssh_argv)
        self.probe_timeout_s = probe_timeout_s

    def _remote_cmd(
        self, name: str, argv: list[str], env: dict[str, str] | None
    ) -> str:
        import shlex

        exports = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in sorted((env or {}).items())
        )
        cmd = " ".join(shlex.quote(a) for a in argv)
        # PIO_WORKER_NAME tags the remote process so signal() can pkill
        # exactly this worker and nothing else on the box
        tag = f"PIO_WORKER_NAME={shlex.quote(name)}"
        return f"exec env {tag} {exports} {cmd}".replace("  ", " ")

    def spawn(
        self,
        host: HostSpec,
        name: str,
        argv: list[str],
        env: dict[str, str] | None = None,
    ) -> Any:
        fh = None if self.logbook is None else self.logbook.open_for(name)
        kw: dict[str, Any] = {}
        if fh is not None:
            kw["stdout"] = fh
            kw["stderr"] = subprocess.STDOUT
        target = host.address or host.name
        try:
            return subprocess.Popen(
                [*self.ssh_argv, target, self._remote_cmd(name, argv, env)],
                **kw,
            )
        finally:
            if fh is not None:
                fh.close()

    def signal(self, host: HostSpec, name: str, handle: Any, sig: int) -> None:
        target = host.address or host.name
        signame = {_signal.SIGTERM: "TERM", _signal.SIGKILL: "KILL"}.get(
            sig, str(int(sig))
        )
        try:
            subprocess.run(
                [
                    *self.ssh_argv,
                    target,
                    f"pkill -{signame} -f PIO_WORKER_NAME={name}",
                ],
                timeout=self.probe_timeout_s,
                capture_output=True,
            )
        except (OSError, subprocess.SubprocessError):
            # the remote signal failing (host gone) falls back to the
            # hangup path: killing the client tears the session down
            pass
        try:
            handle.send_signal(sig)
        except (OSError, ValueError):
            pass

    def fetch_log_tail(
        self, host: HostSpec, name: str, max_bytes: int = 8192
    ) -> str:
        # the ssh client's stdout == the remote worker's stdout, so the
        # local logbook already holds the evidence
        if self.logbook is None:
            return ""
        return self.logbook.tail(name, max_bytes)

    def probe(self, host: HostSpec) -> bool:
        target = host.address or host.name
        try:
            rc = subprocess.run(
                [*self.ssh_argv, target, "true"],
                timeout=self.probe_timeout_s,
                capture_output=True,
            ).returncode
        except (OSError, subprocess.SubprocessError):
            return False
        return rc == 0


class ContainerHostDriver(HostDriver):
    """Workers inside docker/podman containers, driven purely through
    the engine CLI (no SDK dep). Each worker is one attached
    ``<engine> run --rm`` client (stdout -> logbook); signals route
    through ``<engine> kill -s`` so the container's pid 1 receives them;
    the probe asks the engine daemon for liveness."""

    kind = DRIVER_CONTAINER

    def __init__(
        self,
        logbook: WorkerLogBook | None = None,
        engine: str | None = None,
        extra_run_args: tuple[str, ...] = ("--network", "host"),
        probe_timeout_s: float = 5.0,
    ):
        self.logbook = logbook
        self.engine = engine or self._find_engine()
        self.extra_run_args = list(extra_run_args)
        self.probe_timeout_s = probe_timeout_s

    @staticmethod
    def _find_engine() -> str:
        for cand in ("docker", "podman"):
            if shutil.which(cand):
                return cand
        return "docker"  # fail loudly at spawn time with the real error

    @staticmethod
    def container_name(host: HostSpec, name: str) -> str:
        return f"pio-{host.name}-{name}".replace("/", "-").replace(":", "-")

    def spawn(
        self,
        host: HostSpec,
        name: str,
        argv: list[str],
        env: dict[str, str] | None = None,
    ) -> Any:
        fh = None if self.logbook is None else self.logbook.open_for(name)
        kw: dict[str, Any] = {}
        if fh is not None:
            kw["stdout"] = fh
            kw["stderr"] = subprocess.STDOUT
        env_args: list[str] = []
        for k, v in sorted((env or {}).items()):
            env_args += ["-e", f"{k}={v}"]
        cname = self.container_name(host, name)
        image = host.address or host.name
        try:
            return subprocess.Popen(
                [
                    self.engine,
                    "run",
                    "--rm",
                    "--name",
                    cname,
                    *self.extra_run_args,
                    *env_args,
                    image,
                    *argv,
                ],
                **kw,
            )
        finally:
            if fh is not None:
                fh.close()

    def signal(self, host: HostSpec, name: str, handle: Any, sig: int) -> None:
        signame = {_signal.SIGTERM: "TERM", _signal.SIGKILL: "KILL"}.get(
            sig, str(int(sig))
        )
        try:
            subprocess.run(
                [
                    self.engine,
                    "kill",
                    "-s",
                    signame,
                    self.container_name(host, name),
                ],
                timeout=self.probe_timeout_s,
                capture_output=True,
            )
        except (OSError, subprocess.SubprocessError):
            try:
                handle.send_signal(sig)
            except (OSError, ValueError):
                pass

    def fetch_log_tail(
        self, host: HostSpec, name: str, max_bytes: int = 8192
    ) -> str:
        if self.logbook is not None:
            local = self.logbook.tail(name, max_bytes)
            if local:
                return local
        try:
            out = subprocess.run(
                [
                    self.engine,
                    "logs",
                    "--tail",
                    "100",
                    self.container_name(host, name),
                ],
                timeout=self.probe_timeout_s,
                capture_output=True,
            )
            return (out.stdout + out.stderr).decode(
                "utf-8", errors="replace"
            )[-max_bytes:]
        except (OSError, subprocess.SubprocessError):
            return ""

    def probe(self, host: HostSpec) -> bool:
        try:
            rc = subprocess.run(
                [self.engine, "info", "--format", "{{.ID}}"],
                timeout=self.probe_timeout_s,
                capture_output=True,
            ).returncode
        except (OSError, subprocess.SubprocessError):
            return False
        return rc == 0


class FakeHostDriver(HostDriver):
    """Chaos-grade fake: REAL local processes, partitioned under fake
    host names, each host with a liveness switch. ``kill_host()``
    SIGKILLs every resident process and flips the probe to dead —
    indistinguishable, from the supervisor's chair, from pulling the
    power cord on a box. The two-host survive-host-death CI gate runs on
    this driver so it needs no second machine and no container engine."""

    kind = DRIVER_FAKE

    def __init__(self, logbook: WorkerLogBook | None = None):
        self.logbook = logbook
        self._local = LocalHostDriver(logbook)
        self._alive: dict[str, bool] = {}
        self._resident: dict[str, dict[str, Any]] = {}  # host -> name -> proc

    def spawn(
        self,
        host: HostSpec,
        name: str,
        argv: list[str],
        env: dict[str, str] | None = None,
    ) -> Any:
        if not self._alive.setdefault(host.name, True):
            raise OSError(f"fake host {host.name!r} is down")
        proc = self._local.spawn(host, name, argv, env)
        self._resident.setdefault(host.name, {})[name] = proc
        return proc

    def signal(self, host: HostSpec, name: str, handle: Any, sig: int) -> None:
        self._local.signal(host, name, handle, sig)

    def fetch_log_tail(
        self, host: HostSpec, name: str, max_bytes: int = 8192
    ) -> str:
        return self._local.fetch_log_tail(host, name, max_bytes)

    def probe(self, host: HostSpec) -> bool:
        return self._alive.setdefault(host.name, True)

    def kill_host(self, host_name: str) -> int:
        """Pull the cord: SIGKILL every resident process, fail the probe
        from now on. Returns how many processes died."""
        self._alive[host_name] = False
        killed = 0
        for proc in self._resident.get(host_name, {}).values():
            if proc.poll() is None:
                try:
                    proc.kill()
                    killed += 1
                except OSError:
                    pass
        logger.warning(
            "fake host %s killed (%d resident processes)", host_name, killed
        )
        return killed

    def revive_host(self, host_name: str) -> None:
        self._alive[host_name] = True
        self._resident.pop(host_name, None)


def make_driver(
    kind: str, logbook: WorkerLogBook | None = None
) -> HostDriver:
    if kind == DRIVER_LOCAL:
        return LocalHostDriver(logbook)
    if kind == DRIVER_SSH:
        return SshHostDriver(logbook)
    if kind == DRIVER_CONTAINER:
        return ContainerHostDriver(logbook)
    if kind == DRIVER_FAKE:
        return FakeHostDriver(logbook)
    raise ValueError(f"unknown host driver {kind!r}")


class HostRuntime:
    """The inventory + its drivers: one shared driver instance per
    driver kind (the fake driver's host-liveness state must be shared
    across hosts it serves), spawn/signal/tail routed by the worker's
    home host, and the probe the supervisor's host-death detection
    polls."""

    def __init__(
        self,
        hosts: list[HostSpec],
        logbook: WorkerLogBook | None = None,
        drivers: dict[str, HostDriver] | None = None,
    ):
        if not hosts:
            raise ValueError("HostRuntime needs at least one host")
        self._hosts = {h.name: h for h in hosts}
        if len(self._hosts) != len(hosts):
            raise ValueError("duplicate host names in inventory")
        self.logbook = logbook
        self._drivers: dict[str, HostDriver] = dict(drivers or {})
        for h in hosts:
            if h.driver not in self._drivers:
                self._drivers[h.driver] = make_driver(h.driver, logbook)

    # ------------------------------------------------------------- inventory
    def hosts(self) -> list[HostSpec]:
        return list(self._hosts.values())

    def host(self, name: str) -> HostSpec:
        try:
            return self._hosts[name]
        except KeyError:
            raise KeyError(f"unknown host {name!r} in fleet inventory") from None

    def driver_for(self, host_name: str) -> HostDriver:
        return self._drivers[self.host(host_name).driver]

    def total_slots(self) -> int:
        return sum(h.slots for h in self._hosts.values())

    # ------------------------------------------------------------- operations
    def spawn_worker(
        self,
        host_name: str,
        worker_name: str,
        argv: list[str],
        env: dict[str, str] | None = None,
    ) -> Any:
        host = self.host(host_name)
        return self.driver_for(host_name).spawn(host, worker_name, argv, env)

    def signal_worker(
        self, host_name: str, worker_name: str, handle: Any, sig: int
    ) -> None:
        host = self.host(host_name)
        self.driver_for(host_name).signal(host, worker_name, handle, sig)

    def log_tail(
        self, host_name: str, worker_name: str, max_bytes: int = 8192
    ) -> str:
        host = self.host(host_name)
        return self.driver_for(host_name).fetch_log_tail(
            host, worker_name, max_bytes
        )

    def probe(self, host_name: str) -> bool:
        host = self.host(host_name)
        try:
            return bool(self.driver_for(host_name).probe(host))
        except Exception:
            logger.exception("host probe failed for %s", host_name)
            return False


def assign_hosts(
    n: int,
    hosts: list[HostSpec],
    taken: dict[str, int] | None = None,
) -> list[str]:
    """Boot-time placement: deal ``n`` workers across the inventory
    breadth-first (fill hosts evenly, never past their slots). Raises
    ``ValueError`` when the inventory is too small — a fleet that cannot
    fit should refuse to boot, not overfill a box."""
    load = {h.name: 0 for h in hosts}
    for name, count in (taken or {}).items():
        if name in load:
            load[name] = count
    order = list(hosts)
    out: list[str] = []
    for _ in range(n):
        free = [h for h in order if load[h.name] < h.slots]
        if not free:
            total = sum(h.slots for h in hosts)
            raise ValueError(
                f"host inventory has {total} slots but "
                f"{n + sum((taken or {}).values())} workers requested "
                "(grow --hosts or shrink --fleet)"
            )
        pick = min(free, key=lambda h: (load[h.name] / h.slots, h.name))
        load[pick.name] += 1
        out.append(pick.name)
    return out


__all__ = [
    "ContainerHostDriver",
    "DRIVER_CONTAINER",
    "DRIVER_FAKE",
    "DRIVER_LOCAL",
    "DRIVER_SSH",
    "FakeHostDriver",
    "HostDriver",
    "HostRuntime",
    "HostSpec",
    "LocalHostDriver",
    "SshHostDriver",
    "assign_hosts",
    "make_driver",
    "parse_hosts",
]
