"""Worker supervision: spawn N QueryServer processes, restart crashes.

The reference PredictionIO leans on Spark's driver/executor supervision
for fault tolerance; here the serving fleet gets the same property
directly: a :class:`Supervisor` owns N worker processes on a port range,
polls their liveness, and restarts a crashed worker with exponential
backoff. A worker that crash-loops (more than ``crash_loop_budget``
exits inside ``crash_loop_window_s``) is *parked* — restarting a worker
that dies on startup forever only burns CPU and log volume; the parked
state is visible in metrics (``pio_fleet_worker_parked``) and the
gateway simply keeps routing around the missing replica.

The process handle and the clock are injectable so the restart policy is
unit-testable without real processes or real sleeping; production use
passes a ``subprocess.Popen`` factory (see ``fleet/launch.py``).

With a :class:`~predictionio_tpu.fleet.worklog.WorkerLogBook` attached,
every crash captures the worker's stderr/stdout tail (the spawn factory
routes the child's fds into the logbook — see ``worklog.spawn_with_log``)
and hands it to the ``on_crash`` hook, which the fleet launcher wires to
the incident flight recorder: a SIGKILLed or crash-looping replica
leaves an inspectable bundle, not a silent restart counter.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Protocol

from predictionio_tpu.fleet.worklog import WorkerLogBook
from predictionio_tpu.obs.metrics import MetricsRegistry

logger = logging.getLogger(__name__)


class ProcessHandle(Protocol):
    """The slice of ``subprocess.Popen`` the supervisor needs."""

    pid: int

    def poll(self) -> int | None: ...

    def terminate(self) -> None: ...

    def kill(self) -> None: ...


REPLICA_CLASS_DEVICE = "device"
REPLICA_CLASS_CPU = "cpu-fallback"


@dataclasses.dataclass(frozen=True)
class WorkerSpec:
    """One replica slot: a stable name (metric label, restart identity),
    the port its QueryServer binds, and its replica class — ``device``
    (accelerator-bound, the latency path) or ``cpu-fallback`` (cheap
    overflow capacity the gateway routes to only when the device class
    is saturated; docs/fleet.md §Autoscaling)."""

    name: str
    port: int
    worker_class: str = REPLICA_CLASS_DEVICE

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


@dataclasses.dataclass
class SupervisorConfig:
    poll_interval_s: float = 0.5
    # exponential restart backoff: crash k (consecutive) waits
    # min(base * mult**k, max) before the respawn
    backoff_base_s: float = 0.5
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 30.0
    # a worker alive this long resets its consecutive-crash count (the
    # backoff ladder is for crash *loops*, not for a crash a week apart)
    healthy_reset_s: float = 30.0
    # crash-loop budget: more than this many exits inside the window
    # parks the worker instead of restarting it again
    crash_loop_window_s: float = 60.0
    crash_loop_budget: int = 5
    # graceful stop: SIGTERM (workers drain), wait this long, then SIGKILL
    term_grace_s: float = 15.0


class _Worker:
    __slots__ = (
        "spec",
        "proc",
        "started_at",
        "consecutive_crashes",
        "crash_times",
        "next_restart_at",
        "parked",
        "restarts",
        "retiring",
        "retire_deadline",
    )

    def __init__(self, spec: WorkerSpec):
        self.spec = spec
        self.proc: ProcessHandle | None = None
        self.started_at = 0.0
        self.consecutive_crashes = 0
        self.crash_times: list[float] = []
        self.next_restart_at = 0.0
        self.parked = False
        self.restarts = 0  # respawns after a crash (not the initial spawn)
        # scale-in state: a retiring worker was SIGTERMed to drain; its
        # exit is a completion, not a crash, and it is never respawned
        self.retiring = False
        self.retire_deadline = 0.0


class Supervisor:
    """Spawn, watch, restart. ``tick()`` is the whole policy — drive it
    from an asyncio loop (:meth:`run`) or directly from tests with a
    fake clock."""

    def __init__(
        self,
        spawn: Callable[[WorkerSpec], ProcessHandle],
        specs: list[WorkerSpec],
        config: SupervisorConfig | None = None,
        metrics: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
        logbook: WorkerLogBook | None = None,
        on_crash: Callable[[dict[str, Any]], None] | None = None,
    ):
        self._spawn = spawn
        self.config = config or SupervisorConfig()
        self._clock = clock
        self._workers = [_Worker(spec) for spec in specs]
        self._stopping = False
        # crash evidence plumbing: the logbook tails the dead worker's
        # captured output, on_crash (the incident-recorder hook) gets one
        # dict per exit with the tail attached
        self.logbook = logbook
        self._on_crash = on_crash
        m = metrics or MetricsRegistry()
        self.metrics = m
        self._m_restarts = m.counter(
            "pio_fleet_restarts_total",
            "supervisor respawns of crashed workers, by replica",
            labelnames=("replica",),
        )
        self._m_crash_loops = m.counter(
            "pio_fleet_crash_loops_total",
            "workers parked for exceeding the crash-loop budget",
            labelnames=("replica",),
        )
        self._m_up = m.gauge(
            "pio_fleet_worker_up",
            "1 when the supervised worker process is running",
            labelnames=("replica",),
        )
        self._m_parked = m.gauge(
            "pio_fleet_worker_parked",
            "1 when the worker exceeded its crash-loop budget and was parked",
            labelnames=("replica",),
        )
        self._m_last_crash = m.gauge(
            "pio_fleet_worker_last_crash_unix",
            "unix time of the worker's most recent exit (0 = never crashed)",
            labelnames=("replica",),
        )
        self._m_log_info = m.gauge(
            "pio_fleet_worker_log_info",
            "1 per worker whose output is captured; the `path` label is "
            "where the rotating tail lives (`pio top --fleet` shows it "
            "for crashed workers)",
            labelnames=("replica", "path"),
        )
        self._m_retired = m.counter(
            "pio_fleet_retired_total",
            "workers retired by a scale-in (graceful SIGTERM drain, never "
            "respawned), by replica class",
            labelnames=("worker_class",),
        )
        if self.logbook is not None:
            for w in self._workers:
                self._m_log_info.set(
                    1.0,
                    replica=w.spec.name,
                    path=self.logbook.path(w.spec.name),
                )
        m.register_collector(self._collect)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Initial spawn of every worker."""
        for w in self._workers:
            self._start_worker(w)

    def _start_worker(self, w: _Worker) -> None:
        try:
            w.proc = self._spawn(w.spec)
        except Exception:
            # a failed spawn is accounted exactly like an instant crash so
            # the backoff/park machinery bounds it too
            logger.exception("spawn failed for worker %s", w.spec.name)
            w.proc = None
            self._record_crash(w)
            return
        w.started_at = self._clock()
        logger.info(
            "worker %s up (pid %s, port %d)",
            w.spec.name,
            getattr(w.proc, "pid", "?"),
            w.spec.port,
        )

    def tick(self) -> None:
        """One supervision pass: reap exits, schedule/execute restarts,
        escalate and reap retiring (scale-in) workers."""
        if self._stopping:
            return
        now = self._clock()
        for w in list(self._workers):
            if w.retiring:
                self._tick_retiring(w, now)
                continue
            if w.parked:
                continue
            if w.proc is None:
                if now >= w.next_restart_at:
                    w.restarts += 1
                    self._m_restarts.inc(replica=w.spec.name)
                    self._start_worker(w)
                continue
            rc = w.proc.poll()
            if rc is None:
                if (
                    w.consecutive_crashes
                    and now - w.started_at >= self.config.healthy_reset_s
                ):
                    w.consecutive_crashes = 0
                continue
            logger.warning(
                "worker %s (port %d) exited rc=%s", w.spec.name, w.spec.port, rc
            )
            w.proc = None
            self._record_crash(w, rc=rc)

    def _tick_retiring(self, w: _Worker, now: float) -> None:
        """Drive one retiring worker: already gone -> reap; past the
        drain grace -> SIGKILL (reaped on a later tick)."""
        rc = w.proc.poll() if w.proc is not None else 0
        if w.proc is None or rc is not None:
            self._reap_retired(w, rc)
            return
        if now >= w.retire_deadline:
            logger.warning(
                "retiring worker %s ignored SIGTERM for %.0fs; killing",
                w.spec.name,
                self.config.term_grace_s,
            )
            try:
                w.proc.kill()
            except Exception:
                pass
            # one more grace slice for the SIGKILL to be reaped
            w.retire_deadline = now + self.config.poll_interval_s

    def _reap_retired(self, w: _Worker, rc: int | None) -> None:
        self._workers = [x for x in self._workers if x is not w]
        self._prune_series()
        logger.info(
            "worker %s retired (rc=%s); %d workers remain",
            w.spec.name,
            rc,
            len(self._workers),
        )

    # ------------------------------------------------------------ elasticity
    def add_worker(self, spec: WorkerSpec) -> None:
        """Scale-out entry: register + spawn one new worker at runtime.
        The restart/park policy covers it exactly like a boot-time
        worker."""
        if any(w.spec.name == spec.name for w in self._workers):
            raise ValueError(f"worker {spec.name!r} already supervised")
        w = _Worker(spec)
        self._workers.append(w)
        if self.logbook is not None:
            self._m_log_info.set(
                1.0, replica=spec.name, path=self.logbook.path(spec.name)
            )
        self._start_worker(w)

    def retire_worker(self, name: str) -> bool:
        """Scale-in entry: SIGTERM the worker (it drains via the
        ``create_server`` drain path — in-flight answered, listener
        closed) and stop restarting it. The exit is reaped by
        :meth:`tick`, which drops the worker and its per-replica gauges.
        Returns False when no such worker exists. The caller must stop
        routing to the replica BEFORE retiring it (gateway membership
        first, process second) — that ordering is what makes scale-in
        5xx-free."""
        for w in self._workers:
            if w.spec.name != name or w.retiring:
                continue
            w.retiring = True
            # the retire DECISION is the telemetry event (the reap is
            # mechanics); counted here so the scale-in timeline in the
            # exposition matches the moment routing stopped
            self._m_retired.inc(worker_class=w.spec.worker_class)
            w.retire_deadline = self._clock() + self.config.term_grace_s
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
            else:
                # nothing running (crashed/parked): reap immediately
                self._reap_retired(w, None)
            return True
        return False

    def live_specs(self) -> list[WorkerSpec]:
        """Workers that count toward fleet capacity: not parked, not
        retiring — the shape the autoscaler's envelope clamps."""
        return [
            w.spec for w in self._workers if not w.parked and not w.retiring
        ]

    def _prune_series(self) -> None:
        """Reconcile per-replica gauges against the live worker set: a
        retired worker's ``pio_fleet_worker_up``/``parked``/crash/log
        series must drop from the exposition, not render as a live-but-
        down replica forever. Counters (restarts, crash loops) stay —
        they are monotonic history, not live-set claims."""
        live = [w.spec.name for w in self._workers]
        for gauge in (
            self._m_up,
            self._m_parked,
            self._m_last_crash,
            self._m_log_info,
        ):
            gauge.prune("replica", live)

    def _record_crash(self, w: _Worker, rc: int | None = None) -> None:
        now = self._clock()
        w.crash_times.append(now)
        cutoff = now - self.config.crash_loop_window_s
        w.crash_times = [t for t in w.crash_times if t >= cutoff]
        self._m_last_crash.set(time.time(), replica=w.spec.name)
        if len(w.crash_times) > self.config.crash_loop_budget:
            w.parked = True
            self._m_crash_loops.inc(replica=w.spec.name)
            logger.error(
                "worker %s parked: %d exits inside %.0fs (budget %d) — "
                "not restarting; fix the crash and redeploy",
                w.spec.name,
                len(w.crash_times),
                self.config.crash_loop_window_s,
                self.config.crash_loop_budget,
            )
            self._notify_crash(w, rc, parked=True)
            return
        backoff = min(
            self.config.backoff_max_s,
            self.config.backoff_base_s
            * self.config.backoff_multiplier**w.consecutive_crashes,
        )
        w.consecutive_crashes += 1
        w.next_restart_at = now + backoff
        logger.info(
            "worker %s restart in %.2fs (consecutive crash %d)",
            w.spec.name,
            backoff,
            w.consecutive_crashes,
        )
        self._notify_crash(w, rc, parked=False)

    def _notify_crash(self, w: _Worker, rc: int | None, parked: bool) -> None:
        """Hand the crash (with the dead worker's captured stderr tail)
        to the on_crash hook — the incident-recorder wiring. Guarded: the
        flight recorder failing must never stall the restart policy."""
        if self._on_crash is None:
            return
        info: dict[str, Any] = {
            "replica": w.spec.name,
            "port": w.spec.port,
            "rc": rc,
            "parked": parked,
            "restarts": w.restarts,
            "consecutiveCrashes": w.consecutive_crashes,
            "crashesInWindow": len(w.crash_times),
        }
        if self.logbook is not None:
            info["logPath"] = self.logbook.path(w.spec.name)
            info["stderrTail"] = self.logbook.tail(w.spec.name)
        try:
            self._on_crash(info)
        except Exception:
            logger.exception("on_crash hook failed for %s", w.spec.name)

    async def run(self) -> None:
        """Asyncio driver for :meth:`tick` (process polls are non-blocking,
        so ticking on the event loop is fine)."""
        import asyncio

        while not self._stopping:
            self.tick()
            await asyncio.sleep(self.config.poll_interval_s)

    def stop(self) -> None:
        """Graceful fleet stop: SIGTERM every worker (they drain), wait
        ``term_grace_s``, SIGKILL stragglers. Blocking — call from a
        thread/executor when on an event loop."""
        self._stopping = True
        live = [w for w in self._workers if w.proc is not None]
        for w in live:
            try:
                w.proc.terminate()
            except Exception:
                pass
        deadline = self._clock() + self.config.term_grace_s
        while self._clock() < deadline:
            if all(w.proc is None or w.proc.poll() is not None for w in live):
                break
            time.sleep(0.05)
        for w in live:
            if w.proc is not None and w.proc.poll() is None:
                logger.warning(
                    "worker %s ignored SIGTERM for %.0fs; killing",
                    w.spec.name,
                    self.config.term_grace_s,
                )
                try:
                    w.proc.kill()
                except Exception:
                    pass

    # ------------------------------------------------------------- queries
    def _collect(self) -> None:
        for w in self._workers:
            up = w.proc is not None and w.proc.poll() is None
            self._m_up.set(1.0 if up else 0.0, replica=w.spec.name)
            self._m_parked.set(1.0 if w.parked else 0.0, replica=w.spec.name)

    def snapshot(self) -> list[dict[str, Any]]:
        return [
            {
                "name": w.spec.name,
                "port": w.spec.port,
                "pid": getattr(w.proc, "pid", None) if w.proc else None,
                "up": w.proc is not None and w.proc.poll() is None,
                "parked": w.parked,
                "retiring": w.retiring,
                "workerClass": w.spec.worker_class,
                "restarts": w.restarts,
                "consecutiveCrashes": w.consecutive_crashes,
                "logPath": (
                    self.logbook.path(w.spec.name)
                    if self.logbook is not None
                    else None
                ),
            }
            for w in self._workers
        ]

    @property
    def workers(self) -> list[WorkerSpec]:
        return [w.spec for w in self._workers]


def terminate_gracefully(proc: ProcessHandle) -> None:
    """SIGTERM spelled portably (Popen.terminate is SIGTERM on POSIX)."""
    try:
        proc.terminate()
    except (OSError, ValueError):
        pass


__all__ = [
    "ProcessHandle",
    "REPLICA_CLASS_CPU",
    "REPLICA_CLASS_DEVICE",
    "Supervisor",
    "SupervisorConfig",
    "WorkerSpec",
    "terminate_gracefully",
]
