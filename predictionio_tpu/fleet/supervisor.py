"""Worker supervision: spawn N QueryServer processes, restart crashes.

The reference PredictionIO leans on Spark's driver/executor supervision
for fault tolerance; here the serving fleet gets the same property
directly: a :class:`Supervisor` owns N worker processes on a port range,
polls their liveness, and restarts a crashed worker with exponential
backoff. A worker that crash-loops (more than ``crash_loop_budget``
exits inside ``crash_loop_window_s``) is *parked* — restarting a worker
that dies on startup forever only burns CPU and log volume; the parked
state is visible in metrics (``pio_fleet_worker_parked``) and the
gateway simply keeps routing around the missing replica.

The process handle and the clock are injectable so the restart policy is
unit-testable without real processes or real sleeping; production use
passes a ``subprocess.Popen`` factory (see ``fleet/launch.py``).

With a :class:`~predictionio_tpu.fleet.worklog.WorkerLogBook` attached,
every crash captures the worker's stderr/stdout tail (the spawn factory
routes the child's fds into the logbook — see ``worklog.spawn_with_log``)
and hands it to the ``on_crash`` hook, which the fleet launcher wires to
the incident flight recorder: a SIGKILLed or crash-looping replica
leaves an inspectable bundle, not a silent restart counter.
"""

from __future__ import annotations

import dataclasses
import logging
import signal as _signal
import time
from typing import Any, Callable, Protocol

from predictionio_tpu.fleet.hostrt import HostRuntime
from predictionio_tpu.fleet.worklog import WorkerLogBook
from predictionio_tpu.obs.metrics import MetricsRegistry

logger = logging.getLogger(__name__)


class ProcessHandle(Protocol):
    """The slice of ``subprocess.Popen`` the supervisor needs."""

    pid: int

    def poll(self) -> int | None: ...

    def terminate(self) -> None: ...

    def kill(self) -> None: ...


REPLICA_CLASS_DEVICE = "device"
REPLICA_CLASS_CPU = "cpu-fallback"


@dataclasses.dataclass(frozen=True)
class WorkerSpec:
    """One replica slot: a stable name (metric label, restart identity),
    the port its QueryServer binds, and its replica class — ``device``
    (accelerator-bound, the latency path) or ``cpu-fallback`` (cheap
    overflow capacity the gateway routes to only when the device class
    is saturated; docs/fleet.md §Autoscaling). ``host`` is the worker's
    home in the fleet inventory (``--hosts``; the default single-box
    deploy puts everything on ``local``) and ``addr`` is where the
    gateway dials it — loopback unless the host lives elsewhere."""

    name: str
    port: int
    worker_class: str = REPLICA_CLASS_DEVICE
    host: str = "local"
    addr: str = "127.0.0.1"

    @property
    def url(self) -> str:
        return f"http://{self.addr}:{self.port}"


@dataclasses.dataclass
class SupervisorConfig:
    poll_interval_s: float = 0.5
    # exponential restart backoff: crash k (consecutive) waits
    # min(base * mult**k, max) before the respawn
    backoff_base_s: float = 0.5
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 30.0
    # a worker alive this long resets its consecutive-crash count (the
    # backoff ladder is for crash *loops*, not for a crash a week apart)
    healthy_reset_s: float = 30.0
    # crash-loop budget: more than this many exits inside the window
    # parks the worker instead of restarting it again
    crash_loop_window_s: float = 60.0
    crash_loop_budget: int = 5
    # graceful stop: SIGTERM (workers drain), wait this long, then SIGKILL
    term_grace_s: float = 15.0
    # multi-host: host liveness probe cadence and how many consecutive
    # probe failures declare the host dead (every resident worker marked
    # crashed in ONE transition; docs/fleet.md §Multi-host)
    host_probe_interval_s: float = 5.0
    host_probe_failures: int = 1


class _Worker:
    __slots__ = (
        "spec",
        "proc",
        "started_at",
        "consecutive_crashes",
        "crash_times",
        "next_restart_at",
        "parked",
        "restarts",
        "retiring",
        "retire_deadline",
    )

    def __init__(self, spec: WorkerSpec):
        self.spec = spec
        self.proc: ProcessHandle | None = None
        self.started_at = 0.0
        self.consecutive_crashes = 0
        self.crash_times: list[float] = []
        self.next_restart_at = 0.0
        self.parked = False
        self.restarts = 0  # respawns after a crash (not the initial spawn)
        # scale-in state: a retiring worker was SIGTERMed to drain; its
        # exit is a completion, not a crash, and it is never respawned
        self.retiring = False
        self.retire_deadline = 0.0


class _Host:
    """Per-host supervision state: the liveness verdict and the host's
    own crash ladder (host DEATHS back off like worker crashes do — a
    box that flaps shouldn't get its whole worker complement respawned
    at full speed every time the probe blips back)."""

    __slots__ = (
        "name",
        "up",
        "deaths",
        "probe_failures",
        "last_probe_at",
        "down_since",
    )

    def __init__(self, name: str):
        self.name = name
        self.up = True
        self.deaths = 0  # up->down transitions (the host crash ladder)
        self.probe_failures = 0  # consecutive
        self.last_probe_at = 0.0
        self.down_since = 0.0


class Supervisor:
    """Spawn, watch, restart. ``tick()`` is the whole policy — drive it
    from an asyncio loop (:meth:`run`) or directly from tests with a
    fake clock."""

    def __init__(
        self,
        spawn: Callable[[WorkerSpec], ProcessHandle],
        specs: list[WorkerSpec],
        config: SupervisorConfig | None = None,
        metrics: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
        logbook: WorkerLogBook | None = None,
        on_crash: Callable[[dict[str, Any]], None] | None = None,
        runtime: HostRuntime | None = None,
        on_host_down: Callable[[dict[str, Any]], None] | None = None,
    ):
        self._spawn = spawn
        self.config = config or SupervisorConfig()
        self._clock = clock
        self._workers = [_Worker(spec) for spec in specs]
        self._stopping = False
        # crash evidence plumbing: the logbook tails the dead worker's
        # captured output, on_crash (the incident-recorder hook) gets one
        # dict per exit with the tail attached
        self.logbook = logbook
        self._on_crash = on_crash
        # multi-host: the runtime carries the inventory + drivers; None
        # means the classic single-box deploy (every spec homes on
        # "local", no probes, behavior identical to pre-PR-17)
        self._runtime = runtime
        self._on_host_down = on_host_down
        self._hosts: dict[str, _Host] = {}
        if runtime is not None:
            self._hosts = {h.name: _Host(h.name) for h in runtime.hosts()}
        m = metrics or MetricsRegistry()
        self.metrics = m
        self._m_restarts = m.counter(
            "pio_fleet_restarts_total",
            "supervisor respawns of crashed workers, by replica",
            labelnames=("replica",),
        )
        self._m_crash_loops = m.counter(
            "pio_fleet_crash_loops_total",
            "workers parked for exceeding the crash-loop budget",
            labelnames=("replica",),
        )
        self._m_up = m.gauge(
            "pio_fleet_worker_up",
            "1 when the supervised worker process is running",
            labelnames=("replica",),
        )
        self._m_parked = m.gauge(
            "pio_fleet_worker_parked",
            "1 when the worker exceeded its crash-loop budget and was parked",
            labelnames=("replica",),
        )
        self._m_last_crash = m.gauge(
            "pio_fleet_worker_last_crash_unix",
            "unix time of the worker's most recent exit (0 = never crashed)",
            labelnames=("replica",),
        )
        self._m_log_info = m.gauge(
            "pio_fleet_worker_log_info",
            "1 per worker whose output is captured; the `path` label is "
            "where the rotating tail lives (`pio top --fleet` shows it "
            "for crashed workers)",
            labelnames=("replica", "path"),
        )
        self._m_retired = m.counter(
            "pio_fleet_retired_total",
            "workers retired by a scale-in (graceful SIGTERM drain, never "
            "respawned), by replica class",
            labelnames=("worker_class",),
        )
        self._m_host_up = m.gauge(
            "pio_fleet_host_up",
            "1 while the host's liveness probe passes; 0 marks a host "
            "death (every resident worker crashed in one transition)",
            labelnames=("host",),
        )
        self._m_host_slots = m.gauge(
            "pio_fleet_host_slots",
            "worker slots the host offers in the --hosts inventory",
            labelnames=("host",),
        )
        self._m_host_deaths = m.counter(
            "pio_fleet_host_deaths_total",
            "host up->down transitions (probe failed / all residents "
            "gone); one incident bundle each, not one per worker",
            labelnames=("host",),
        )
        self._m_worker_host = m.gauge(
            "pio_fleet_worker_host_info",
            "1 per supervised worker; the `host` label is its home in "
            "the fleet inventory (`pio top --fleet` groups by it)",
            labelnames=("replica", "host"),
        )
        if runtime is not None:
            for h in runtime.hosts():
                self._m_host_up.set(1.0, host=h.name)
                self._m_host_slots.set(float(h.slots), host=h.name)
        for w in self._workers:
            self._m_worker_host.set(
                1.0, replica=w.spec.name, host=w.spec.host
            )
        if self.logbook is not None:
            for w in self._workers:
                self._m_log_info.set(
                    1.0,
                    replica=w.spec.name,
                    path=self.logbook.path(w.spec.name),
                )
        m.register_collector(self._collect)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Initial spawn of every worker."""
        for w in self._workers:
            self._start_worker(w)

    def _start_worker(self, w: _Worker) -> None:
        try:
            w.proc = self._spawn(w.spec)
        except Exception:
            # a failed spawn is accounted exactly like an instant crash so
            # the backoff/park machinery bounds it too
            logger.exception("spawn failed for worker %s", w.spec.name)
            w.proc = None
            self._record_crash(w)
            return
        w.started_at = self._clock()
        logger.info(
            "worker %s up (pid %s, port %d)",
            w.spec.name,
            getattr(w.proc, "pid", "?"),
            w.spec.port,
        )

    def tick(self) -> None:
        """One supervision pass: probe host liveness, reap exits,
        schedule/execute restarts, escalate and reap retiring (scale-in)
        workers. Runs on an executor thread in production (:meth:`run`)
        — host probes and remote spawns block."""
        if self._stopping:
            return
        now = self._clock()
        self._probe_hosts(now)
        exited_by_host: dict[str, list[tuple[_Worker, int | None]]] = {}
        for w in list(self._workers):
            if w.retiring:
                self._tick_retiring(w, now)
                continue
            if w.parked:
                continue
            host = self._hosts.get(w.spec.host)
            if host is not None and not host.up:
                # residents of a dead host wait for the probe to pass
                # again; their restart clock starts at revival
                continue
            if w.proc is None:
                if now >= w.next_restart_at:
                    w.restarts += 1
                    self._m_restarts.inc(replica=w.spec.name)
                    self._start_worker(w)
                continue
            rc = w.proc.poll()
            if rc is None:
                if (
                    w.consecutive_crashes
                    and now - w.started_at >= self.config.healthy_reset_s
                ):
                    w.consecutive_crashes = 0
                continue
            logger.warning(
                "worker %s (port %d) exited rc=%s", w.spec.name, w.spec.port, rc
            )
            w.proc = None
            if host is not None:
                # defer the crash verdict: simultaneous exits on one box
                # smell like a host death, and a host death must be ONE
                # transition, not N worker crashes
                exited_by_host.setdefault(w.spec.host, []).append((w, rc))
            else:
                self._record_crash(w, rc=rc)
        self._judge_exits(exited_by_host, now)

    # -------------------------------------------------------------- hosts
    def _judge_exits(
        self,
        exited_by_host: dict[str, list[tuple[_Worker, int | None]]],
        now: float,
    ) -> None:
        """Exits observed this pass, grouped by host: any resident dying
        triggers an immediate liveness probe; a failed probe converts
        the whole group (plus anything still resident) into one
        host-death transition — even when the dying residents straddled
        two poll ticks, the first lone exit already flips the verdict.
        A passing probe means the workers really did crash
        individually."""
        for host_name, group in exited_by_host.items():
            host = self._hosts[host_name]
            if group and not self._probe_once(host, now):
                self._host_down(host, now, exited=group)
                continue
            for w, rc in group:
                self._record_crash(w, rc=rc)

    def _probe_once(self, host: _Host, now: float) -> bool:
        ok = self._runtime.probe(host.name)
        host.last_probe_at = now
        if ok:
            host.probe_failures = 0
        else:
            host.probe_failures += 1
        return ok

    def _probe_hosts(self, now: float) -> None:
        """Periodic host liveness: a host failing
        ``host_probe_failures`` consecutive probes is declared dead; a
        dead host whose probe passes again is readmitted and its
        residents respawn up the host's backoff ladder."""
        if self._runtime is None:
            return
        for host in self._hosts.values():
            if now - host.last_probe_at < self.config.host_probe_interval_s:
                continue
            ok = self._probe_once(host, now)
            if host.up and not ok:
                if host.probe_failures >= self.config.host_probe_failures:
                    self._host_down(host, now)
            elif not host.up and ok:
                self._host_up(host, now)

    def _host_down(
        self,
        host: _Host,
        now: float,
        exited: list[tuple[_Worker, int | None]] | None = None,
    ) -> None:
        """The host-death transition: every resident worker is marked
        crashed HERE, in one pass — one `on_host_down` notification (one
        incident bundle) carrying every dead worker's log tail, instead
        of N interleaved worker-crash bundles. Residents do NOT climb
        their own crash ladders (the box died, not their code); the
        ladder that moves is the host's."""
        if not host.up:
            return
        host.up = False
        host.deaths += 1
        host.down_since = now
        self._m_host_up.set(0.0, host=host.name)
        self._m_host_deaths.inc(host=host.name)
        dead: list[dict[str, Any]] = []
        exited_names = {w.spec.name for w, _ in (exited or [])}
        for w, rc in exited or []:
            dead.append(self._host_death_entry(w, rc))
        for w in self._workers:
            if (
                w.spec.host != host.name
                or w.retiring
                or w.parked
                or w.spec.name in exited_names
            ):
                continue
            rc = None
            if w.proc is not None:
                # best-effort reap/kill of whatever handle survives (an
                # ssh client to a dead box, a local proc on a fake host)
                try:
                    rc = w.proc.poll()
                    if rc is None:
                        w.proc.kill()
                except (OSError, ValueError):
                    pass
                w.proc = None
            dead.append(self._host_death_entry(w, rc))
        # restart clock: residents become eligible once the host probe
        # passes again, after the host's own backoff ladder
        backoff = min(
            self.config.backoff_max_s,
            self.config.backoff_base_s
            * self.config.backoff_multiplier ** max(0, host.deaths - 1),
        )
        for w in self._workers:
            if w.spec.host == host.name and not w.retiring and not w.parked:
                w.next_restart_at = now + backoff
                self._m_last_crash.set(time.time(), replica=w.spec.name)
        logger.error(
            "host %s DOWN: %d resident workers marked crashed in one "
            "transition (host death #%d)",
            host.name,
            len(dead),
            host.deaths,
        )
        if self._on_host_down is not None:
            info = {
                "host": host.name,
                "deaths": host.deaths,
                "workers": dead,
            }
            try:
                self._on_host_down(info)
            except Exception:
                logger.exception("on_host_down hook failed for %s", host.name)

    def _host_death_entry(
        self, w: _Worker, rc: int | None
    ) -> dict[str, Any]:
        entry: dict[str, Any] = {
            "replica": w.spec.name,
            "port": w.spec.port,
            "workerClass": w.spec.worker_class,
            "rc": rc,
        }
        if self._runtime is not None:
            try:
                entry["logTail"] = self._runtime.log_tail(
                    w.spec.host, w.spec.name
                )
            except Exception:
                entry["logTail"] = ""
        return entry

    def _host_up(self, host: _Host, now: float) -> None:
        host.up = True
        host.down_since = 0.0
        self._m_host_up.set(1.0, host=host.name)
        logger.warning(
            "host %s readmitted after probe recovery; residents respawn "
            "on their restart clocks",
            host.name,
        )

    def host_census(self) -> dict[str, dict[str, Any]]:
        """Inventory view for placement and `pio top`: per host, the
        declared slots, liveness, death count, and resident workers
        (parked included — they hold a slot until retired)."""
        if self._runtime is None:
            return {}
        out: dict[str, dict[str, Any]] = {}
        for h in self._runtime.hosts():
            st = self._hosts[h.name]
            resident = [
                w.spec.name
                for w in self._workers
                if w.spec.host == h.name and not w.retiring
            ]
            out[h.name] = {
                "slots": h.slots,
                "driver": h.driver,
                "up": st.up,
                "deaths": st.deaths,
                "resident": resident,
            }
        return out

    def pick_host(self) -> str | None:
        """Host-aware placement for scale-out: the UP host with the most
        free slot headroom (ties by name). None when every live host is
        full — the autoscaler's envelope should treat that as saturated.
        Without a runtime there is no inventory and placement is the
        caller's 'local' default."""
        if self._runtime is None:
            return None
        best: tuple[float, str] | None = None
        for name, info in self.host_census().items():
            if not info["up"]:
                continue
            free = info["slots"] - len(info["resident"])
            if free <= 0:
                continue
            score = (len(info["resident"]) / info["slots"], name)
            if best is None or score < best:
                best = score
        return best[1] if best else None

    def _tick_retiring(self, w: _Worker, now: float) -> None:
        """Drive one retiring worker: already gone -> reap; past the
        drain grace -> SIGKILL (reaped on a later tick)."""
        rc = w.proc.poll() if w.proc is not None else 0
        if w.proc is None or rc is not None:
            self._reap_retired(w, rc)
            return
        if now >= w.retire_deadline:
            logger.warning(
                "retiring worker %s ignored SIGTERM for %.0fs; killing",
                w.spec.name,
                self.config.term_grace_s,
            )
            self._deliver(w, _signal.SIGKILL)
            # one more grace slice for the SIGKILL to be reaped
            w.retire_deadline = now + self.config.poll_interval_s

    def _reap_retired(self, w: _Worker, rc: int | None) -> None:
        self._workers = [x for x in self._workers if x is not w]
        self._prune_series()
        logger.info(
            "worker %s retired (rc=%s); %d workers remain",
            w.spec.name,
            rc,
            len(self._workers),
        )

    # ------------------------------------------------------------ elasticity
    def add_worker(self, spec: WorkerSpec) -> None:
        """Scale-out entry: register + spawn one new worker at runtime.
        The restart/park policy covers it exactly like a boot-time
        worker."""
        if any(w.spec.name == spec.name for w in self._workers):
            raise ValueError(f"worker {spec.name!r} already supervised")
        if spec.host not in self._hosts and self._runtime is not None:
            raise ValueError(
                f"worker {spec.name!r} homed on unknown host {spec.host!r}"
            )
        w = _Worker(spec)
        self._workers.append(w)
        self._m_worker_host.set(1.0, replica=spec.name, host=spec.host)
        if self.logbook is not None:
            self._m_log_info.set(
                1.0, replica=spec.name, path=self.logbook.path(spec.name)
            )
        self._start_worker(w)

    def retire_worker(self, name: str) -> bool:
        """Scale-in entry: SIGTERM the worker (it drains via the
        ``create_server`` drain path — in-flight answered, listener
        closed) and stop restarting it. The exit is reaped by
        :meth:`tick`, which drops the worker and its per-replica gauges.
        Returns False when no such worker exists. The caller must stop
        routing to the replica BEFORE retiring it (gateway membership
        first, process second) — that ordering is what makes scale-in
        5xx-free."""
        for w in self._workers:
            if w.spec.name != name or w.retiring:
                continue
            w.retiring = True
            # the retire DECISION is the telemetry event (the reap is
            # mechanics); counted here so the scale-in timeline in the
            # exposition matches the moment routing stopped
            self._m_retired.inc(worker_class=w.spec.worker_class)
            w.retire_deadline = self._clock() + self.config.term_grace_s
            if w.proc is not None and w.proc.poll() is None:
                self._deliver(w, _signal.SIGTERM)
            else:
                # nothing running (crashed/parked): reap immediately
                self._reap_retired(w, None)
            return True
        return False

    def live_specs(self) -> list[WorkerSpec]:
        """Workers that count toward fleet capacity: not parked, not
        retiring — the shape the autoscaler's envelope clamps."""
        return [
            w.spec for w in self._workers if not w.parked and not w.retiring
        ]

    def _prune_series(self) -> None:
        """Reconcile per-replica gauges against the live worker set: a
        retired worker's ``pio_fleet_worker_up``/``parked``/crash/log
        series must drop from the exposition, not render as a live-but-
        down replica forever. Counters (restarts, crash loops) stay —
        they are monotonic history, not live-set claims."""
        live = [w.spec.name for w in self._workers]
        for gauge in (
            self._m_up,
            self._m_parked,
            self._m_last_crash,
            self._m_log_info,
            self._m_worker_host,
        ):
            gauge.prune("replica", live)

    def _record_crash(self, w: _Worker, rc: int | None = None) -> None:
        now = self._clock()
        w.crash_times.append(now)
        cutoff = now - self.config.crash_loop_window_s
        w.crash_times = [t for t in w.crash_times if t >= cutoff]
        self._m_last_crash.set(time.time(), replica=w.spec.name)
        if len(w.crash_times) > self.config.crash_loop_budget:
            w.parked = True
            self._m_crash_loops.inc(replica=w.spec.name)
            logger.error(
                "worker %s parked: %d exits inside %.0fs (budget %d) — "
                "not restarting; fix the crash and redeploy",
                w.spec.name,
                len(w.crash_times),
                self.config.crash_loop_window_s,
                self.config.crash_loop_budget,
            )
            self._notify_crash(w, rc, parked=True)
            return
        backoff = min(
            self.config.backoff_max_s,
            self.config.backoff_base_s
            * self.config.backoff_multiplier**w.consecutive_crashes,
        )
        w.consecutive_crashes += 1
        w.next_restart_at = now + backoff
        logger.info(
            "worker %s restart in %.2fs (consecutive crash %d)",
            w.spec.name,
            backoff,
            w.consecutive_crashes,
        )
        self._notify_crash(w, rc, parked=False)

    def _notify_crash(self, w: _Worker, rc: int | None, parked: bool) -> None:
        """Hand the crash (with the dead worker's captured stderr tail)
        to the on_crash hook — the incident-recorder wiring. Guarded: the
        flight recorder failing must never stall the restart policy."""
        if self._on_crash is None:
            return
        info: dict[str, Any] = {
            "replica": w.spec.name,
            "port": w.spec.port,
            "rc": rc,
            "parked": parked,
            "restarts": w.restarts,
            "consecutiveCrashes": w.consecutive_crashes,
            "crashesInWindow": len(w.crash_times),
        }
        if self.logbook is not None:
            info["logPath"] = self.logbook.path(w.spec.name)
            info["stderrTail"] = self.logbook.tail(w.spec.name)
        try:
            self._on_crash(info)
        except Exception:
            logger.exception("on_crash hook failed for %s", w.spec.name)

    def _deliver(self, w: _Worker, sig: int) -> None:
        """Route a signal through the worker's host driver: a remote
        worker needs the FAR side signalled (ssh pkill, container kill),
        not just its local client handle."""
        if w.proc is None:
            return
        try:
            if self._runtime is not None:
                self._runtime.signal_worker(
                    w.spec.host, w.spec.name, w.proc, sig
                )
            elif sig == _signal.SIGKILL:
                w.proc.kill()
            else:
                w.proc.terminate()
        except (OSError, ValueError):
            pass

    async def run(self) -> None:
        """Asyncio driver for :meth:`tick`, each pass on an EXECUTOR
        thread: a multi-host tick blocks (ssh probes, container spawns),
        and even the local driver's spawn is a fork — none of it belongs
        on the serving event loop (the same rule the autoscaler and
        incident captures follow)."""
        import asyncio

        loop = asyncio.get_running_loop()
        while not self._stopping:
            await loop.run_in_executor(None, self.tick)
            await asyncio.sleep(self.config.poll_interval_s)

    def stop(self) -> None:
        """Graceful fleet stop: SIGTERM every worker (they drain), wait
        ``term_grace_s``, SIGKILL stragglers. Blocking — call from a
        thread/executor when on an event loop."""
        self._stopping = True
        live = [w for w in self._workers if w.proc is not None]
        for w in live:
            self._deliver(w, _signal.SIGTERM)
        deadline = self._clock() + self.config.term_grace_s
        while self._clock() < deadline:
            if all(w.proc is None or w.proc.poll() is not None for w in live):
                break
            time.sleep(0.05)
        for w in live:
            if w.proc is not None and w.proc.poll() is None:
                logger.warning(
                    "worker %s ignored SIGTERM for %.0fs; killing",
                    w.spec.name,
                    self.config.term_grace_s,
                )
                self._deliver(w, _signal.SIGKILL)

    # ------------------------------------------------------------- queries
    def _collect(self) -> None:
        for w in self._workers:
            up = w.proc is not None and w.proc.poll() is None
            self._m_up.set(1.0 if up else 0.0, replica=w.spec.name)
            self._m_parked.set(1.0 if w.parked else 0.0, replica=w.spec.name)

    def snapshot(self) -> list[dict[str, Any]]:
        return [
            {
                "name": w.spec.name,
                "port": w.spec.port,
                "host": w.spec.host,
                "pid": getattr(w.proc, "pid", None) if w.proc else None,
                "up": w.proc is not None and w.proc.poll() is None,
                "parked": w.parked,
                "retiring": w.retiring,
                "workerClass": w.spec.worker_class,
                "restarts": w.restarts,
                "consecutiveCrashes": w.consecutive_crashes,
                "logPath": (
                    self.logbook.path(w.spec.name)
                    if self.logbook is not None
                    else None
                ),
            }
            for w in self._workers
        ]

    @property
    def workers(self) -> list[WorkerSpec]:
        return [w.spec for w in self._workers]


def terminate_gracefully(proc: ProcessHandle) -> None:
    """SIGTERM spelled portably (Popen.terminate is SIGTERM on POSIX)."""
    try:
        proc.terminate()
    except (OSError, ValueError):
        pass


__all__ = [
    "ProcessHandle",
    "REPLICA_CLASS_CPU",
    "REPLICA_CLASS_DEVICE",
    "Supervisor",
    "SupervisorConfig",
    "WorkerSpec",
    "terminate_gracefully",
]
