"""SLO-driven elasticity: the fleet sizes itself from the telemetry ring.

PR 6 built the burn-rate engine, PR 9 the supervisor, PR 11 the durable
telemetry ring that connects them; this module closes the loop. A
traffic spike used to end in load-shed 503s until a human resized the
fleet — now a control loop inside ``pio deploy --fleet`` READS the ring
(fleet SLO burn rates, per-replica queue-depth/inflight/shed snapshots,
appended by the gateway every telemetry tick) and drives the supervisor
and the gateway's membership funnel:

- **Scale out** on fast-window SLO burn or sustained queue depth (and
  immediately on observed sheds — the thing the loop exists to prevent).
  New device-class replicas first; when the device envelope is
  exhausted, cheap ``cpu-fallback`` replicas absorb overflow (the
  CPU-serverless-vs-accelerator cost shape: slower answers beat sheds,
  and a CPU replica costs a fraction of a device one).
- **Scale in** on sustained idleness, via the existing graceful drain:
  the gateway stops routing to the victim FIRST (membership funnel),
  then the supervisor SIGTERMs it (the worker answers its in-flight
  queries and exits) — provably 5xx-free, chaos-asserted.
- **Never flap.** Signals must hold across consecutive ring records
  (probe noise is one record), scale-out and scale-in each have their
  own cooldown, and the out/in thresholds are split (hysteresis).
- **Never resize mid-bake.** The registry rollout state is consulted
  every tick; a resize wanted while a candidate bakes is DEFERRED and
  fires after the promote/rollback lands.
- **Bounded.** A min/max replica envelope per class; wanting to scale
  past it is an incident (``autoscaler-saturated``), not a surprise.

The decision engine (:class:`ScalingPolicy`) is a pure unit — fake
clock + fake ring records drive every branch without a process — in the
same injectable style as the supervisor's restart policy. The
:class:`Autoscaler` wraps it with the ring/registry/supervisor/gateway
plumbing, appends each decision back to the ring (``kind="scaling"`` —
``pio top --history`` renders them as markers) and exports the
``pio_autoscaler_*`` family (docs/observability.md).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Any, Callable

from predictionio_tpu.fleet.gateway import Gateway
from predictionio_tpu.fleet.supervisor import (
    REPLICA_CLASS_CPU,
    REPLICA_CLASS_DEVICE,
    Supervisor,
    WorkerSpec,
)
from predictionio_tpu.obs.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

# decision actions
SCALE_OUT = "scale-out"
SCALE_IN = "scale-in"
HOLD = "hold"
DEFER = "defer"


@dataclasses.dataclass
class AutoscalerConfig:
    # device-class envelope; cpu_fallback_max bounds the overflow class
    # (0 disables heterogeneous replicas entirely)
    min_replicas: int = 1
    max_replicas: int = 4
    cpu_fallback_max: int = 0
    tick_interval_s: float = 5.0
    # how much ring history one decision reads
    lookback_s: float = 600.0
    # scale-out: any fleet SLO's FAST-window burn at/over this rate...
    burn_threshold: float = 1.0
    # ...or queue depth per healthy replica at/over this...
    queue_depth_high: float = 8.0
    # ...or gateway in-flight per healthy replica at/over this (the
    # tier-above view: a worker can drain its own queue fast while the
    # fleet still runs hot on concurrency; reads the per-tick PEAK when
    # the snapshot carries one — instant samples alias under bursty
    # event-loop scheduling)...
    inflight_high_per_replica: float = 16.0
    # fraction of confirm-window records that must show pressure (>= 2
    # records regardless): all-records was brittle — one aliased cold
    # sample inside an otherwise hot window vetoed a needed scale-out
    confirm_fraction: float = 0.8
    # ...held across EVERY record in this trailing window (>= 2 records:
    # one pressured snapshot is probe noise, not a trend). Sheds inside
    # the window trigger regardless — a shed is never noise.
    confirm_s: float = 10.0
    # scale-in: every record across this window idle (queue below the
    # LOW watermark — split from the high one: hysteresis — inflight per
    # replica low, burn cold, zero sheds)
    idle_sustain_s: float = 120.0
    queue_depth_low: float = 0.5
    idle_inflight_per_replica: float = 1.0
    idle_burn_max: float = 0.25
    # flap damping: no second scale-out/in sooner than this after any
    # applied resize
    scale_out_cooldown_s: float = 30.0
    scale_in_cooldown_s: float = 120.0
    # replicas added/retired per decision
    scale_step: int = 1


@dataclasses.dataclass(frozen=True)
class FleetShape:
    """Live capacity by class (parked and retiring workers excluded)."""

    device: int = 0
    cpu: int = 0

    @property
    def total(self) -> int:
        return self.device + self.cpu


@dataclasses.dataclass(frozen=True)
class Decision:
    """One tick's verdict. ``action`` is scale-out/scale-in/hold/defer;
    ``reason`` is the triggering signal (burn/queue/shed/idle/cooldown/
    mid-bake/saturated/at-floor/...); ``replica_class`` names which class
    resizes; ``deferred`` marks a resumed mid-bake deferral."""

    action: str
    reason: str
    replica_class: str | None = None
    step: int = 0
    deferred: bool = False

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "action": self.action,
            "reason": self.reason,
            "class": self.replica_class,
            "step": self.step,
            "deferred": self.deferred,
        }


def _fast_burn(record: dict[str, Any]) -> float:
    """The hottest FAST-window burn across the record's fleet SLOs (the
    fast window is the smallest one — same rule ``pio top --history``
    renders)."""
    worst = 0.0
    for state in (record.get("slo") or {}).values():
        burn = state.get("burn") or {}
        if not burn:
            continue
        fast = min(burn, key=float)
        worst = max(worst, float(burn.get(fast, 0.0)))
    return worst


def _healthy_count(record: dict[str, Any]) -> int:
    return sum(
        1
        for rep in (record.get("replicas") or {}).values()
        if rep.get("healthy")
    )


def _shed_total(record: dict[str, Any]) -> float:
    counters = record.get("counters") or {}
    return float(counters.get("no_replica", 0.0)) + float(
        counters.get("load_shed", 0.0)
    )


class ScalingPolicy:
    """The pure decision engine: ring records in, :class:`Decision` out.

    Stateful only in what elasticity needs — last applied resize (for
    cooldowns) and the pending mid-bake deferral — and every method takes
    an explicit ``now`` so tests drive it with a fake clock and
    hand-built records. The caller MUST confirm an applied resize via
    :meth:`note_applied`; a decision that could not be executed leaves
    the cooldown clock untouched."""

    def __init__(self, config: AutoscalerConfig | None = None):
        self.config = config or AutoscalerConfig()
        self._last_out_at: float | None = None
        self._last_in_at: float | None = None
        self.pending: Decision | None = None

    # ------------------------------------------------------------- signals
    def _window(
        self, records: list[dict[str, Any]], now: float, seconds: float
    ) -> list[dict[str, Any]]:
        cutoff = now - seconds
        return [
            r
            for r in records
            if r.get("kind", "fleet") == "fleet"
            and float(r.get("t", 0.0)) >= cutoff
        ]

    def _pressure_reason(self, record: dict[str, Any]) -> str | None:
        cfg = self.config
        if _fast_burn(record) >= cfg.burn_threshold:
            return "burn"
        healthy = max(1, _healthy_count(record))
        gauges = record.get("gauges") or {}
        if float(gauges.get("queue_depth", 0.0)) / healthy >= cfg.queue_depth_high:
            return "queue-depth"
        inflight = max(
            float(gauges.get("inflight", 0.0)),
            float(gauges.get("inflight_peak", 0.0)),
        )
        if inflight / healthy >= cfg.inflight_high_per_replica:
            return "inflight"
        return None

    def _pressured(self, record: dict[str, Any]) -> bool:
        return self._pressure_reason(record) is not None

    def wants_scale_out(
        self, records: list[dict[str, Any]], now: float
    ) -> str | None:
        """Pressure reason when the trailing confirm window demands more
        capacity, else None. Sheds anywhere in the window trigger even a
        single-record signal — by the time a shed is in the ring, users
        already saw 503s."""
        recent = self._window(records, now, self.config.confirm_s)
        if not recent:
            return None
        # shed DELTA across the confirm window: baseline = the newest
        # record just OUTSIDE it, so a shed from minutes ago can never
        # re-trigger once traffic recovered (counters are cumulative;
        # replica restarts can lower federated sums, hence the clamp)
        before = [
            r
            for r in self._window(records, now, self.config.lookback_s)
            if float(r.get("t", 0.0)) < now - self.config.confirm_s
        ]
        baseline = _shed_total(before[-1]) if before else _shed_total(recent[0])
        shed_delta = max(0.0, _shed_total(recent[-1]) - baseline)
        if shed_delta > 0:
            # a fresh shed triggers ALONE: users already saw 503s, and
            # the newest record sampling calm (clients backing off, the
            # peak gauge just consumed) must not veto the response
            return "shed"
        if len(recent) < 2:
            return None
        pressured = [r for r in recent if self._pressured(r)]
        if len(pressured) / len(recent) >= self.config.confirm_fraction:
            return self._pressure_reason(pressured[-1])
        return None

    def wants_scale_in(
        self, records: list[dict[str, Any]], now: float, shape: FleetShape
    ) -> bool:
        """True when the whole idle_sustain window shows a cold fleet.
        The window must actually be COVERED (oldest record near its far
        edge) — two records ten seconds apart must not vouch for two
        minutes of idleness."""
        cfg = self.config
        # select 25% past the sustain window: the record that PROVES
        # coverage sits near the window edge and must not fall off it
        # between being written and being read (idleness slightly older
        # than the window is still idleness)
        recent = self._window(records, now, cfg.idle_sustain_s * 1.25)
        if len(recent) < 2:
            return False
        oldest_t = float(recent[0].get("t", now))
        if now - oldest_t < cfg.idle_sustain_s * 0.8:
            return False
        sheds = _shed_total(recent[-1]) - _shed_total(recent[0])
        if sheds > 0:
            return False
        per_replica = max(1, shape.total)
        for r in recent:
            gauges = r.get("gauges") or {}
            if float(gauges.get("queue_depth", 0.0)) > cfg.queue_depth_low:
                return False
            # idle means PEAK concurrency stayed low, not that the tick
            # happened to sample an idle instant
            inflight = max(
                float(gauges.get("inflight", 0.0)),
                float(gauges.get("inflight_peak", 0.0)),
            )
            if inflight / per_replica > cfg.idle_inflight_per_replica:
                return False
            if _fast_burn(r) > cfg.idle_burn_max:
                return False
        return True

    # ------------------------------------------------------------ clamping
    def _clamp_out(self, shape: FleetShape, reason: str) -> Decision:
        cfg = self.config
        if shape.device < cfg.max_replicas:
            return Decision(SCALE_OUT, reason, REPLICA_CLASS_DEVICE, cfg.scale_step)
        if shape.cpu < cfg.cpu_fallback_max:
            # device envelope exhausted: cheap overflow capacity
            return Decision(SCALE_OUT, reason, REPLICA_CLASS_CPU, cfg.scale_step)
        return Decision(HOLD, "saturated")

    def _clamp_in(self, shape: FleetShape) -> Decision:
        cfg = self.config
        if shape.cpu > 0:
            # retire overflow capacity first: it is the slow class, and
            # dropping it restores the homogeneous fast-path fleet
            return Decision(SCALE_IN, "idle", REPLICA_CLASS_CPU, cfg.scale_step)
        if shape.device > cfg.min_replicas:
            return Decision(SCALE_IN, "idle", REPLICA_CLASS_DEVICE, cfg.scale_step)
        return Decision(HOLD, "at-floor")

    # ------------------------------------------------------------- deciding
    def decide(
        self,
        records: list[dict[str, Any]],
        shape: FleetShape,
        rollout_active: bool,
        now: float,
    ) -> Decision:
        """One tick: evaluate signals over the ring records (oldest
        first), apply hysteresis/cooldowns/clamps/rollout-awareness."""
        cfg = self.config
        # a deferred resize fires as soon as the bake ends — re-clamped
        # against the CURRENT shape (which may have drifted: crash/park)
        # and re-validated against the CURRENT signal: the world moved
        # while the bake ran, and a deferred scale-in applied into a
        # fresh spike would retire capacity at peak load (the 503s this
        # loop exists to prevent). A contradicted deferral dissolves; a
        # merely-stale one (signal neutral) still fires, as promised.
        if self.pending is not None and not rollout_active:
            pend = self.pending
            contradicted = (
                self.wants_scale_in(records, now, shape)
                if pend.action == SCALE_OUT
                else self.wants_scale_out(records, now) is not None
            )
            if contradicted:
                self.pending = None
                return Decision(HOLD, f"deferred-{pend.action}-contradicted")
            if pend.action == SCALE_OUT:
                resumed = self._clamp_out(shape, pend.reason)
            else:
                resumed = self._clamp_in(shape)
            if resumed.action in (SCALE_OUT, SCALE_IN):
                return dataclasses.replace(resumed, deferred=True)
            self.pending = None  # clamp says the resize no longer applies
            return resumed
        out_reason = self.wants_scale_out(records, now)
        if out_reason is not None:
            decision = self._clamp_out(shape, out_reason)
            if decision.action != SCALE_OUT:
                return decision  # saturated
            if rollout_active:
                return self._defer(decision, f"mid-bake:{out_reason}")
            if (
                self._last_out_at is not None
                and now - self._last_out_at < cfg.scale_out_cooldown_s
            ):
                return Decision(HOLD, "cooldown-out")
            return decision
        if self.wants_scale_in(records, now, shape):
            decision = self._clamp_in(shape)
            if decision.action != SCALE_IN:
                return decision  # at-floor
            if rollout_active:
                return self._defer(decision, "mid-bake:idle")
            last_any = max(
                (t for t in (self._last_out_at, self._last_in_at) if t is not None),
                default=None,
            )
            if last_any is not None and now - last_any < cfg.scale_in_cooldown_s:
                return Decision(HOLD, "cooldown-in")
            return decision
        return Decision(HOLD, "steady")

    def _defer(self, decision: Decision, reason: str) -> Decision:
        """Remember one resize for after the bake. DEFER is an EPISODE:
        the same resize re-wanted on later ticks of the same bake updates
        the pending slot silently (HOLD) so the deferred counter counts
        resizes deferred, not ticks spent baking, and the bounded ring
        gets one scaling record per deferral, not one per tick."""
        already = self.pending is not None and (
            self.pending.action,
            self.pending.replica_class,
        ) == (decision.action, decision.replica_class)
        self.pending = decision
        if already:
            return Decision(HOLD, "mid-bake-pending", decision.replica_class)
        return Decision(DEFER, reason, decision.replica_class)

    def note_applied(self, decision: Decision, now: float) -> None:
        """The caller executed the resize: start its cooldown and clear
        any pending deferral it satisfied."""
        if decision.action == SCALE_OUT:
            self._last_out_at = now
        elif decision.action == SCALE_IN:
            self._last_in_at = now
        if decision.deferred:
            self.pending = None


class Autoscaler:
    """The control loop: ring -> :class:`ScalingPolicy` -> supervisor +
    gateway membership funnel, with every decision appended back to the
    ring and exported as ``pio_autoscaler_*``.

    ``spec_factory(worker_class)`` allocates the next
    :class:`~predictionio_tpu.fleet.supervisor.WorkerSpec` (name + port)
    for a scale-out — port allocation lives with the launcher, which
    knows the fleet's port range. ``rollout_probe`` returns True while
    any engine's rollout is mid-bake (the launcher wires it to the
    registry; None means "no registry, never defer")."""

    def __init__(
        self,
        policy: ScalingPolicy,
        supervisor: Supervisor,
        gateway: Gateway,
        spec_factory: Callable[[str], WorkerSpec],
        ring: Any | None = None,  # obs.tsring.TelemetryRing
        rollout_probe: Callable[[], bool] | None = None,
        metrics: MetricsRegistry | None = None,
        incidents: Any | None = None,  # obs.incidents.IncidentRecorder
        clock: Callable[[], float] = time.time,
    ):
        self.policy = policy
        self.supervisor = supervisor
        self.gateway = gateway
        self.ring = ring
        self._spec_factory = spec_factory
        self._rollout_probe = rollout_probe
        self.incidents = incidents
        self._clock = clock
        self._was_saturated = False
        m = metrics or MetricsRegistry()
        self.metrics = m
        cfg = policy.config
        self._m_ticks = m.counter(
            "pio_autoscaler_ticks_total", "autoscaler control-loop passes"
        )
        self._m_errors = m.counter(
            "pio_autoscaler_errors_total",
            "autoscaler ticks that failed (ring read, registry probe, or "
            "resize execution)",
        )
        self._m_outs = m.counter(
            "pio_autoscaler_scale_outs_total",
            "replicas added by the autoscaler, by class",
            labelnames=("worker_class",),
        )
        self._m_ins = m.counter(
            "pio_autoscaler_scale_ins_total",
            "replicas retired (drain-based) by the autoscaler, by class",
            labelnames=("worker_class",),
        )
        self._m_deferred = m.counter(
            "pio_autoscaler_deferred_total",
            "resizes deferred because a rollout was mid-bake (applied "
            "after promote/rollback)",
        )
        self._m_saturated = m.counter(
            "pio_autoscaler_saturated_total",
            "ticks that wanted capacity past the whole envelope "
            "(device max + cpu-fallback max) — each saturation episode "
            "also snapshots an incident bundle",
        )
        self._m_replicas = m.gauge(
            "pio_autoscaler_replicas",
            "live fleet shape as the autoscaler sees it, by class "
            "(parked/retiring workers excluded)",
            labelnames=("worker_class",),
        )
        self._m_min = m.gauge(
            "pio_autoscaler_replicas_min", "device-class envelope floor"
        )
        self._m_max = m.gauge(
            "pio_autoscaler_replicas_max", "device-class envelope ceiling"
        )
        self._m_cpu_max = m.gauge(
            "pio_autoscaler_cpu_fallback_max",
            "cpu-fallback (overflow) class ceiling; 0 = class disabled",
        )
        self._m_last_scale_unix = m.gauge(
            "pio_autoscaler_last_scale_unix",
            "unix time of the last applied resize (0 = never)",
        )
        self._m_min.set(float(cfg.min_replicas))
        self._m_max.set(float(cfg.max_replicas))
        self._m_cpu_max.set(float(cfg.cpu_fallback_max))
        m.register_collector(self._collect)

    # ------------------------------------------------------------- plumbing
    def _collect(self) -> None:
        shape = self.shape()
        self._m_replicas.set(float(shape.device), worker_class=REPLICA_CLASS_DEVICE)
        self._m_replicas.set(float(shape.cpu), worker_class=REPLICA_CLASS_CPU)

    def shape(self) -> FleetShape:
        device = cpu = 0
        for spec in self.supervisor.live_specs():
            if spec.worker_class == REPLICA_CLASS_CPU:
                cpu += 1
            else:
                device += 1
        return FleetShape(device=device, cpu=cpu)

    def rollout_active(self) -> bool:
        # raises on an unreadable registry: this tick must not resize on
        # unknown rollout state (run() counts the error and retries)
        if self._rollout_probe is None:
            return False
        return bool(self._rollout_probe())

    def _ring_records(self) -> list[dict[str, Any]]:
        if self.ring is None:
            return []
        records = self.ring.window(self.policy.config.lookback_s)
        # multi-gateway tier: every peer writes fleet snapshots into the
        # shared ring (namespaced segments). The policy's windows assume
        # one snapshot per tick, so scale off the PRIMARY gateway's view
        # — conservative under a balancer that splits traffic evenly,
        # and correct for membership because resizes fan out to peers.
        gw_id = getattr(
            getattr(self.gateway, "config", None), "gateway_id", None
        )
        if gw_id:
            records = [
                r for r in records if r.get("gateway") in (None, gw_id)
            ]
        return records

    def _record_decision(self, decision: Decision, shape: FleetShape) -> None:
        """Scaling decisions are telemetry: appended to the SAME ring the
        policy reads, so `pio top --history`, incident bundles, and the
        next operator all see why the fleet is the size it is."""
        if self.ring is None:
            return
        self.ring.append(
            {
                "kind": "scaling",
                "decision": decision.to_json_dict(),
                "shape": {"device": shape.device, "cpu": shape.cpu},
            }
        )

    # ---------------------------------------------------------------- tick
    def tick(self) -> Decision:
        """One control pass: read the ring, decide, execute. Exceptions
        propagate to the caller (:meth:`run` counts them); a failed
        resize never marks the policy's cooldown."""
        self._m_ticks.inc()
        now = self._clock()
        records = self._ring_records()
        shape = self.shape()
        decision = self.policy.decide(
            records, shape, self.rollout_active(), now
        )
        self.apply(decision, shape, now)
        return decision

    def apply(
        self,
        decision: Decision,
        shape: FleetShape | None = None,
        now: float | None = None,
    ) -> None:
        """Execute one decision through the membership funnel (also the
        CI smoke's entry point for a deterministic scale cycle)."""
        shape = self.shape() if shape is None else shape
        now = self._clock() if now is None else now
        if decision.action == SCALE_OUT:
            self._scale_out(decision, shape, now)
        elif decision.action == SCALE_IN:
            self._scale_in(decision, shape, now)
        elif decision.action == DEFER:
            self._m_deferred.inc()
            self._record_decision(decision, shape)
            logger.info("autoscaler: resize deferred (%s)", decision.reason)
        elif decision.reason == "saturated":
            self._m_saturated.inc()
            if not self._was_saturated:
                # episode transition, not a per-tick spam: the bundle
                # carries the ring tail that shows the unmet pressure
                self._record_decision(decision, shape)
                if self.incidents is not None:
                    self.incidents.trigger(
                        "autoscaler-saturated",
                        context={
                            "shape": {"device": shape.device, "cpu": shape.cpu},
                            "maxReplicas": self.policy.config.max_replicas,
                            "cpuFallbackMax": self.policy.config.cpu_fallback_max,
                        },
                    )
            self._was_saturated = True
        if decision.reason != "saturated":
            self._was_saturated = False

    def _scale_out(self, decision: Decision, shape: FleetShape, now: float) -> None:
        for _ in range(max(1, decision.step)):
            spec = self._spec_factory(decision.replica_class or REPLICA_CLASS_DEVICE)
            self.supervisor.add_worker(spec)
            self.gateway.add_replica(spec.url, spec.worker_class)
            self._m_outs.inc(worker_class=spec.worker_class)
            logger.info(
                "autoscaler: scale-out %s (%s, port %d) on %s",
                spec.name,
                spec.worker_class,
                spec.port,
                decision.reason,
            )
        self._m_last_scale_unix.set(now)
        self.policy.note_applied(decision, now)
        self._record_decision(decision, self.shape())

    def _scale_in(self, decision: Decision, shape: FleetShape, now: float) -> None:
        cls = decision.replica_class or REPLICA_CLASS_DEVICE
        victims = [s for s in self.supervisor.live_specs() if s.worker_class == cls]
        if not victims:
            logger.warning("autoscaler: no %s worker left to retire", cls)
            return
        retired = 0
        for spec in reversed(victims[-max(1, decision.step):]):
            # routing stops FIRST (membership funnel), the process drains
            # second — the ordering that keeps scale-in 5xx-free
            self.gateway.retire_replica(spec.url)
            self.supervisor.retire_worker(spec.name)
            self._m_ins.inc(worker_class=spec.worker_class)
            retired += 1
            logger.info(
                "autoscaler: scale-in %s (%s) on %s",
                spec.name,
                spec.worker_class,
                decision.reason,
            )
        if retired:
            self._m_last_scale_unix.set(now)
            self.policy.note_applied(decision, now)
            self._record_decision(decision, self.shape())

    # ----------------------------------------------------------------- run
    async def run(self) -> None:
        """Asyncio driver: tick forever at the configured cadence; a
        failing tick is counted and retried next interval (an autoscaler
        crash-looping out of existence is exactly the 'autoscaler dead'
        failure-matrix row).

        Each tick runs on an EXECUTOR thread, never the serving event
        loop: a tick walks the on-disk ring, reads registry state files,
        and (on a resize) spawns a process — all blocking I/O that would
        stall every in-flight proxy exactly during a spike, when the
        loop is busiest (the same rule PR 11 applied to incident
        captures). The pieces a tick touches are thread-safe: the
        gateway's membership funnel holds its lock, the ring read is
        file-level, and the supervisor's worker-list mutations are the
        same calls ``supervisor.stop`` already makes from an executor."""
        interval = self.policy.config.tick_interval_s
        loop = asyncio.get_running_loop()
        while True:
            try:
                await loop.run_in_executor(None, self.tick)
            except asyncio.CancelledError:
                raise
            except Exception:
                self._m_errors.inc()
                logger.exception("autoscaler tick failed")
            await asyncio.sleep(interval)


# The never-act-mid-bake probe moved to the registry package (PR 19)
# so the autoscaler and the lifecycle controller share ONE definition of
# "rollout active"; re-exported here for existing importers.
from predictionio_tpu.registry.probe import registry_rollout_probe  # noqa: E402

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "Decision",
    "FleetShape",
    "ScalingPolicy",
    "registry_rollout_probe",
]
