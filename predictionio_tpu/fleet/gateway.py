"""The fleet gateway: one ingress, N QueryServer replicas, zero-downtime.

Same stack as the servers (aiohttp) so a fleet deploy adds one moving
part, not a new runtime. Responsibilities:

- **Routing.** ``POST /queries.json`` goes to the least-loaded routable
  replica (fewest in-flight proxied requests); ties break on a
  consistent hash of the query's sticky key, so equal-load fleets still
  route a user deterministically and per-replica caches see repeat
  traffic. A replica is *routable* when its ``/healthz`` probe passes
  and its circuit breaker admits traffic. When EVERY replica has failed
  its last probe, routing goes *panic mode* — health is ignored
  (breakers still apply), because a fleet-wide probe blackout is more
  often a probe artifact than a dead fleet.
- **Ejection / readmission.** A background probe loop GETs every
  replica's ``/healthz`` each ``probe_interval_s``; a failing or
  unreachable replica is ejected (counted) and readmitted when the
  probe passes again. Independently, each replica has a
  :class:`~predictionio_tpu.resilience.CircuitBreaker` fed by proxy
  outcomes — consecutive forward failures stop traffic within the
  breaker threshold, faster than the next probe.
- **Retry.** /queries.json is idempotent (pure reads), so a forward
  that dies (connection error or replica 5xx) is retried ONCE on a
  different replica — never on a 4xx (the client's error follows them
  to any replica), never for the non-idempotent admin proxies, and
  bounded by the PR-2 :class:`~predictionio_tpu.resilience.RetryBudget`
  so a dying fleet sees load drop, not double.
- **Drain.** SIGTERM stops the listener (new connections refused at
  TCP), keeps answering requests that arrive on established keep-alive
  connections — with ``Connection: close`` so clients migrate — waits
  for in-flight proxies to finish (bounded by ``drain_grace_s``), then
  exits. A gateway restart under a process supervisor is 5xx-free.
- **Federation.** ``GET /metrics`` merges every replica's scrape with
  the gateway's own ``pio_fleet_*``/``pio_gateway_*`` instruments
  (:mod:`.federation`) — the endpoint ``pio top --fleet`` reads.

Model-rollout admin (``GET /models``, ``POST /models/*``) proxies to one
healthy replica; the change lands in the shared registry and every other
replica adopts it through its registry-sync loop (``docs/fleet.md``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
from typing import Any
from urllib.parse import urlsplit

import aiohttp
from aiohttp import web

from predictionio_tpu.fleet.federation import federate_metrics
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import TRACE_HEADER, mint_trace_id
from predictionio_tpu.obs.web import (
    BreakerInstruments,
    PROMETHEUS_CONTENT_TYPE,
)
from predictionio_tpu.registry.router import routing_key, sticky_bucket
from predictionio_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    RetryBudget,
)

logger = logging.getLogger(__name__)

# forward outcomes that justify trying a different replica: transport
# failures and replica-side 5xx. 4xx is the CLIENT's problem — it would
# fail identically everywhere, and re-dispatching it doubles load for
# nothing.
RETRIABLE_STATUSES = frozenset((500, 502, 503, 504))


@dataclasses.dataclass
class GatewayConfig:
    ip: str = "0.0.0.0"
    port: int = 8000
    replica_urls: tuple[str, ...] = ()
    # /healthz probe cadence and per-probe timeout (ejection latency is
    # bounded by interval + timeout)
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    # per-forward total timeout (connect + response)
    request_timeout_s: float = 10.0
    # one-retry budget: each first attempt earns `ratio` tokens, each
    # retry spends 1 (resilience.RetryBudget semantics)
    retry_budget_ratio: float = 0.2
    # per-replica breaker: consecutive forward failures before the
    # gateway stops routing there without waiting for the next probe
    breaker_threshold: int = 3
    breaker_recovery_s: float = 5.0
    # consistent-hash tie-break key (same field the servers use for
    # sticky canary routing)
    sticky_key_field: str = "user"
    max_payload_bytes: int = 1 << 20
    shed_retry_after_s: float = 1.0
    drain_grace_s: float = 15.0


class Replica:
    """Gateway-side state for one backend QueryServer."""

    def __init__(self, url: str, breaker: CircuitBreaker):
        self.url = url.rstrip("/")
        split = urlsplit(self.url)
        self.name = split.netloc or self.url
        self.breaker = breaker
        # healthy-until-proven-otherwise: the first probe fires
        # immediately at startup, and the breaker bounds the damage of
        # routing to a replica that was never up
        self.healthy = True
        # a replica that has never passed a probe is "not up yet", not
        # "ejected": startup must not inflate the ejection counter
        self.ever_ready = False
        self.inflight = 0

    def snapshot(self) -> dict[str, Any]:
        return {
            "url": self.url,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "breaker": self.breaker.snapshot(),
        }


class Gateway:
    def __init__(
        self,
        config: GatewayConfig,
        metrics: MetricsRegistry | None = None,
    ):
        if not config.replica_urls:
            raise ValueError("gateway needs at least one replica URL")
        self.config = config
        self.metrics = metrics or MetricsRegistry()
        m = self.metrics
        self._breaker_instruments = BreakerInstruments(m)
        self.replicas = [
            Replica(
                url,
                self._breaker_instruments.watch(
                    CircuitBreaker(
                        name=f"replica:{urlsplit(url.rstrip('/')).netloc or url}",
                        failure_threshold=config.breaker_threshold,
                        recovery_timeout_s=config.breaker_recovery_s,
                    )
                ),
            )
            for url in config.replica_urls
        ]
        self.retry_budget = RetryBudget(ratio=config.retry_budget_ratio)
        self._m_replicas = m.gauge(
            "pio_fleet_replicas", "replicas configured behind this gateway"
        )
        self._m_replicas.set(len(self.replicas))
        self._m_up = m.gauge(
            "pio_fleet_replica_up",
            "1 when the replica's last /healthz probe passed",
            labelnames=("replica",),
        )
        self._m_inflight = m.gauge(
            "pio_fleet_replica_inflight",
            "queries currently proxied to the replica",
            labelnames=("replica",),
        )
        self._m_requests = m.counter(
            "pio_fleet_requests_total",
            "queries proxied, by replica and upstream status class",
            labelnames=("replica", "status"),
        )
        self._m_ejections = m.counter(
            "pio_fleet_ejections_total",
            "replicas ejected on a failed /healthz probe",
            labelnames=("replica",),
        )
        self._m_readmissions = m.counter(
            "pio_fleet_readmissions_total",
            "ejected replicas readmitted on a passing /healthz probe",
            labelnames=("replica",),
        )
        self._m_retries = m.counter(
            "pio_fleet_retries_total",
            "queries retried on a different replica after a forward failure",
        )
        self._m_no_replica = m.counter(
            "pio_fleet_no_replica_total",
            "queries shed because no routable replica existed",
        )
        self._m_panic = m.counter(
            "pio_fleet_panic_picks_total",
            "queries routed in panic mode: every replica failed its last "
            "probe, so health was ignored (breakers still applied)",
        )
        self._m_latency = m.histogram(
            "pio_gateway_request_seconds",
            "gateway e2e proxy wall time (ingress to upstream answer relayed)",
            labelnames=("endpoint",),
        )
        m.register_collector(self._collect)
        self._session: aiohttp.ClientSession | None = None
        self._probe_task: asyncio.Task | None = None
        self._runner: web.AppRunner | None = None
        self._draining = False
        self._inflight_requests = 0
        self._stop_event = asyncio.Event()
        self._drain_task: asyncio.Task | None = None

    # ------------------------------------------------------------- plumbing
    def _collect(self) -> None:
        for r in self.replicas:
            self._m_up.set(1.0 if r.healthy else 0.0, replica=r.name)
            self._m_inflight.set(float(r.inflight), replica=r.name)

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=self.config.request_timeout_s
                )
            )
        return self._session

    # -------------------------------------------------------------- routing
    def pick_replica(
        self, key: str, exclude: frozenset[str] = frozenset()
    ) -> Replica | None:
        """Least-loaded routable replica; consistent-hash tie-break.

        Claims a breaker slot (``allow()``) on the winner — the caller
        MUST pair the pick with ``record_success``/``record_failure``.
        """
        pool = [r for r in self.replicas if r.name not in exclude]
        candidates = [r for r in pool if r.healthy]
        if not candidates and pool:
            # panic routing: EVERY replica failed its last probe. Probes
            # are advisory — one can time out against a loaded-but-alive
            # worker — and when the whole fleet looks down at once, the
            # probes being wrong is likelier than the fleet being dead.
            # Route across all of them; the per-replica breakers still
            # gate backends that are truly gone.
            candidates = pool
            self._m_panic.inc()
        if not candidates:
            return None
        low = min(r.inflight for r in candidates)
        tied = sorted(
            (r for r in candidates if r.inflight == low),
            key=lambda r: r.name,
        )
        # rotate the tie list by the sticky hash: same key -> same replica
        # while loads stay equal, different keys spread uniformly
        start = int(sticky_bucket(key) * len(tied)) % len(tied)
        for i in range(len(tied)):
            r = tied[(start + i) % len(tied)]
            try:
                r.breaker.allow()
            except CircuitOpenError:
                continue
            return r
        # every tied replica's breaker refused; try the rest by load
        rest = sorted(
            (r for r in candidates if r.inflight != low),
            key=lambda r: (r.inflight, r.name),
        )
        for r in rest:
            try:
                r.breaker.allow()
            except CircuitOpenError:
                continue
            return r
        return None

    async def _forward(
        self,
        replica: Replica,
        method: str,
        path: str,
        body: bytes | None,
        headers: dict[str, str],
    ) -> tuple[int, bytes, str]:
        """One proxied request. Returns (status, body, content_type);
        raises on transport failure. Replica accounting (inflight,
        breaker, counters) is the caller's job — retry logic needs to
        see the raw outcome."""
        replica.inflight += 1
        try:
            async with self._http().request(
                method, f"{replica.url}{path}", data=body, headers=headers
            ) as resp:
                payload = await resp.read()
                return (
                    resp.status,
                    payload,
                    resp.headers.get("Content-Type", "application/json"),
                )
        finally:
            replica.inflight -= 1

    @staticmethod
    def _status_class(status: int) -> str:
        return f"{status // 100}xx"

    def _record_outcome(self, replica: Replica, status: int) -> None:
        self._m_requests.inc(
            replica=replica.name, status=self._status_class(status)
        )
        if status in RETRIABLE_STATUSES:
            # replica-side trouble: feeds the breaker like a transport
            # failure (a 503-shedding replica needs backing off from too)
            replica.breaker.record_failure()
        else:
            # 2xx obviously; 4xx too — the *replica* answered fine, the
            # client's request was bad. 4xx must not trip a breaker.
            replica.breaker.record_success()

    # --------------------------------------------------------------- routes
    async def handle_queries(self, request: web.Request) -> web.Response:
        t0 = time.perf_counter()
        try:
            return await self._handle_queries_inner(request)
        finally:
            self._m_latency.observe(
                time.perf_counter() - t0, endpoint="/queries.json"
            )

    async def _handle_queries_inner(self, request: web.Request) -> web.Response:
        if (
            self.config.max_payload_bytes
            and request.content_length is not None
            and request.content_length > self.config.max_payload_bytes
        ):
            return web.json_response(
                {"message": "query payload too large"}, status=413
            )
        body = await request.read()
        # sticky key for the consistent-hash tie-break; a non-JSON body
        # still routes (the replica will 400 it properly)
        try:
            key = routing_key(json.loads(body), self.config.sticky_key_field)
        except (ValueError, TypeError):
            key = body.decode("utf-8", errors="replace")
        trace_id = request.headers.get(TRACE_HEADER) or mint_trace_id()
        headers = {
            "Content-Type": "application/json",
            TRACE_HEADER: trace_id,
        }
        self._inflight_requests += 1
        try:
            resp = await self._route_query(key, body, headers)
        finally:
            self._inflight_requests -= 1
        resp.headers[TRACE_HEADER] = trace_id
        if self._draining:
            # drain keeps ANSWERING: the listener is closed (new
            # connections refused at TCP), but a request arriving on an
            # established keep-alive connection is served — 503ing it
            # would be the 5xx the drain exists to avoid. Connection:
            # close winds the keep-alive down so the client reconnects
            # elsewhere and the drain converges.
            resp.force_close()
        return resp

    async def _route_query(
        self, key: str, body: bytes, headers: dict[str, str]
    ) -> web.Response:
        self.retry_budget.record_attempt()
        first = self.pick_replica(key)
        if first is None:
            self._m_no_replica.inc()
            return self._unavailable(
                "no healthy replica available", self.config.shed_retry_after_s
            )
        failure: tuple[int, bytes, str] | None = None
        try:
            status, payload, ctype = await self._forward(
                first, "POST", "/queries.json", body, headers
            )
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            first.breaker.record_failure()
            self._m_requests.inc(replica=first.name, status="error")
            logger.warning("forward to %s failed: %s", first.name, exc)
        else:
            self._record_outcome(first, status)
            if status not in RETRIABLE_STATUSES:
                return web.Response(
                    body=payload, status=status, content_type=_bare(ctype)
                )
            failure = (status, payload, ctype)
        # one retry on a DIFFERENT replica — /queries.json is idempotent
        # (pure read), so re-dispatch cannot double-apply anything
        if self.retry_budget.try_spend():
            second = self.pick_replica(key, exclude=frozenset((first.name,)))
            if second is not None:
                self._m_retries.inc()
                try:
                    status, payload, ctype = await self._forward(
                        second, "POST", "/queries.json", body, headers
                    )
                except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                    second.breaker.record_failure()
                    self._m_requests.inc(replica=second.name, status="error")
                    logger.warning(
                        "retry forward to %s failed: %s", second.name, exc
                    )
                else:
                    self._record_outcome(second, status)
                    return web.Response(
                        body=payload, status=status, content_type=_bare(ctype)
                    )
        if failure is not None:
            # relay the replica's own 5xx rather than masking it
            status, payload, ctype = failure
            return web.Response(
                body=payload, status=status, content_type=_bare(ctype)
            )
        return self._unavailable(
            "replica unavailable and retry failed",
            self.config.shed_retry_after_s,
        )

    async def _proxy_admin(
        self, request: web.Request, method: str, path: str
    ) -> web.Response:
        """Single-dispatch proxy for the non-idempotent rollout admin
        surface: exactly ONE replica sees the request (the registry is
        the fan-out — every other replica adopts the state change via
        its sync loop). Never retried: a promote that timed out may
        still have landed."""
        replica = self.pick_replica(path)
        if replica is None:
            return self._unavailable(
                "no healthy replica available", self.config.shed_retry_after_s
            )
        body = await request.read() if request.can_read_body else None
        try:
            status, payload, ctype = await self._forward(
                replica,
                method,
                path,
                body,
                {"Content-Type": "application/json"},
            )
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            replica.breaker.record_failure()
            self._m_requests.inc(replica=replica.name, status="error")
            return self._unavailable(
                f"replica {replica.name} unreachable: {exc}",
                self.config.shed_retry_after_s,
            )
        self._record_outcome(replica, status)
        return web.Response(body=payload, status=status, content_type=_bare(ctype))

    async def handle_models(self, request: web.Request) -> web.Response:
        return await self._proxy_admin(request, "GET", "/models")

    async def handle_models_post(self, request: web.Request) -> web.Response:
        action = request.match_info["action"]
        if action not in ("candidate", "promote", "rollback"):
            return web.json_response({"message": "unknown action"}, status=404)
        return await self._proxy_admin(request, "POST", f"/models/{action}")

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Federated fleet scrape: every reachable replica's /metrics
        merged (counters summed, histogram buckets added) plus the
        gateway's own pio_fleet_* instruments."""
        texts = [self.metrics.render_prometheus()]
        results = await asyncio.gather(
            *(self._fetch_metrics(r) for r in self.replicas)
        )
        texts.extend(t for t in results if t is not None)
        return web.Response(
            text=federate_metrics(texts),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    async def _fetch_metrics(self, replica: Replica) -> str | None:
        try:
            async with self._http().get(
                f"{replica.url}/metrics",
                timeout=aiohttp.ClientTimeout(total=self.config.probe_timeout_s),
            ) as resp:
                if resp.status != 200:
                    return None
                return await resp.text()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return None

    async def handle_healthz(self, request: web.Request) -> web.Response:
        healthy = sum(1 for r in self.replicas if r.healthy)
        ready = healthy > 0 and not self._draining
        return web.json_response(
            {
                "ready": ready,
                "draining": self._draining,
                "replicasHealthy": healthy,
                "replicasTotal": len(self.replicas),
            },
            status=200 if ready else 503,
        )

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "alive",
                "role": "gateway",
                "draining": self._draining,
                "replicas": [r.snapshot() for r in self.replicas],
                "retryBudgetTokens": self.retry_budget.tokens,
            }
        )

    async def handle_stop(self, request: web.Request) -> web.Response:
        self._stop_event.set()
        return web.json_response({"message": "Stopping."})

    @staticmethod
    def _unavailable(message: str, retry_after_s: float) -> web.Response:
        return web.json_response(
            {"message": message},
            status=503,
            headers={"Retry-After": str(max(1, round(retry_after_s)))},
        )

    # ---------------------------------------------------------------- probes
    async def _probe_loop(self) -> None:
        while True:
            try:
                await asyncio.gather(
                    *(self._probe(r) for r in self.replicas)
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("probe pass failed")
            await asyncio.sleep(self.config.probe_interval_s)

    async def _probe(self, replica: Replica) -> None:
        try:
            async with self._http().get(
                f"{replica.url}/healthz",
                timeout=aiohttp.ClientTimeout(total=self.config.probe_timeout_s),
            ) as resp:
                ok = resp.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            ok = False
        if ok:
            if not replica.healthy:
                replica.healthy = True
                if replica.ever_ready:
                    self._m_readmissions.inc(replica=replica.name)
                    logger.info("replica %s readmitted", replica.name)
                else:
                    logger.info("replica %s up", replica.name)
            replica.ever_ready = True
        elif replica.healthy:
            replica.healthy = False
            if replica.ever_ready:
                self._m_ejections.inc(replica=replica.name)
                logger.warning(
                    "replica %s ejected (failed /healthz)", replica.name
                )
            else:
                logger.info("replica %s not ready yet", replica.name)

    # ------------------------------------------------------------- lifecycle
    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_status),
                web.get("/healthz", self.handle_healthz),
                web.get("/metrics", self.handle_metrics),
                web.post("/queries.json", self.handle_queries),
                web.get("/models", self.handle_models),
                web.post("/models/{action}", self.handle_models_post),
                web.post("/stop", self.handle_stop),
            ]
        )

        async def _start_probes(app: web.Application) -> None:
            self._probe_task = asyncio.ensure_future(self._probe_loop())

        async def _cleanup(app: web.Application) -> None:
            task = self._probe_task
            self._probe_task = None
            if task is not None:
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)
            if self._session is not None and not self._session.closed:
                await self._session.close()
            self._session = None

        app.on_startup.append(_start_probes)
        app.on_cleanup.append(_cleanup)
        return app

    async def start(self) -> None:
        self._runner = web.AppRunner(self.make_app(), access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.ip, self.config.port)
        await site.start()
        logger.info(
            "fleet gateway on %s:%d (%d replicas)",
            self.config.ip,
            self.config.port,
            len(self.replicas),
        )

    async def drain(self) -> None:
        """Stop accepting, answer in-flight, then return. Idempotent."""
        if self._draining:
            return
        self._draining = True
        logger.info(
            "gateway drain: listener closing, %d in flight",
            self._inflight_requests,
        )
        if self._runner is not None:
            for site in list(self._runner.sites):
                try:
                    await site.stop()
                except Exception:
                    pass
        deadline = time.monotonic() + max(0.0, self.config.drain_grace_s)
        while self._inflight_requests > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if self._inflight_requests:
            logger.warning(
                "gateway drain grace expired with %d requests in flight",
                self._inflight_requests,
            )

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def run_until_stopped(self) -> None:
        await self.start()
        await self._stop_event.wait()
        await self.drain()
        await self.stop()

    def begin_drain(self) -> None:
        """Signal-handler entry: drain, then release run_until_stopped.
        The task is held on its own attribute — the event loop keeps only
        a weak reference, and a GC'd drain task would leave SIGTERM
        hanging forever."""

        async def _go() -> None:
            await self.drain()
            self._stop_event.set()

        self._drain_task = asyncio.ensure_future(_go())


def _bare(content_type: str) -> str:
    """aiohttp's Response(content_type=...) rejects parameters; strip
    ``; charset=...`` from a proxied upstream header."""
    return content_type.split(";", 1)[0].strip() or "application/json"


__all__ = ["Gateway", "GatewayConfig", "Replica", "RETRIABLE_STATUSES"]
