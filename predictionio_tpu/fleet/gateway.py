"""The fleet gateway: one ingress, N QueryServer replicas, zero-downtime.

Same stack as the servers (aiohttp) so a fleet deploy adds one moving
part, not a new runtime. Responsibilities:

- **Routing.** ``POST /queries.json`` goes to the least-loaded routable
  replica (fewest in-flight proxied requests); ties break on a
  consistent hash of the query's sticky key, so equal-load fleets still
  route a user deterministically and per-replica caches see repeat
  traffic. A replica is *routable* when its ``/healthz`` probe passes
  and its circuit breaker admits traffic. When EVERY replica has failed
  its last probe, routing goes *panic mode* — health is ignored
  (breakers still apply), because a fleet-wide probe blackout is more
  often a probe artifact than a dead fleet.
- **Ejection / readmission.** A background probe loop GETs every
  replica's ``/healthz`` each ``probe_interval_s``; a failing or
  unreachable replica is ejected (counted) and readmitted when the
  probe passes again. Independently, each replica has a
  :class:`~predictionio_tpu.resilience.CircuitBreaker` fed by proxy
  outcomes — consecutive forward failures stop traffic within the
  breaker threshold, faster than the next probe.
- **Retry.** /queries.json is idempotent (pure reads), so a forward
  that dies (connection error or replica 5xx) is retried ONCE on a
  different replica — never on a 4xx (the client's error follows them
  to any replica), never for the non-idempotent admin proxies, and
  bounded by the PR-2 :class:`~predictionio_tpu.resilience.RetryBudget`
  so a dying fleet sees load drop, not double.
- **Drain.** SIGTERM stops the listener (new connections refused at
  TCP), keeps answering requests that arrive on established keep-alive
  connections — with ``Connection: close`` so clients migrate — waits
  for in-flight proxies to finish (bounded by ``drain_grace_s``), then
  exits. A gateway restart under a process supervisor is 5xx-free.
- **Federation.** ``GET /metrics`` merges every replica's scrape with
  the gateway's own ``pio_fleet_*``/``pio_gateway_*`` instruments
  (:mod:`.federation`) — the endpoint ``pio top --fleet`` reads.
- **Cross-tier tracing.** Every routed query is recorded as real spans
  on the ingress trace id: ``gateway.route`` (replica chosen,
  healthy-replica count, panic/retry attribution, final status) and one
  ``gateway.proxy`` per forward attempt (upstream wall time per
  replica) — the gateway hop ``bench.py`` prices is attributable per
  request. ``GET /traces/recent`` fan-in merges the gateway's own span
  ring with each replica's (fetched live from healthy replicas, served
  from the per-tick cache for dead ones — a SIGKILLed worker's last
  spans survive it); ``?trace_id=`` assembles one gateway→replica
  waterfall, which is where a federated p99 exemplar resolves.
- **Telemetry ring + fleet SLOs.** Each telemetry tick (probe cadence
  by default) the gateway federates the fleet's counters, evaluates
  fleet-level SLOs over the federated deltas (:mod:`obs.slo` burn-rate
  engine — availability, the paper's <10 ms p50, shed), and appends a
  snapshot (per-replica health/inflight/breaker, queue depth, burn
  rates) to the durable on-disk :class:`~predictionio_tpu.obs.tsring.
  TelemetryRing` — the history ``GET /telemetry/window?s=N`` and
  ``pio top --history`` serve, and the sensory input a future
  autoscaler reads.
- **Incident triggers.** A fleet SLO flipping to alerting, a replica
  breaker tripping open, or a 5xx escaping to a client (the zero-5xx
  invariant the chaos suite asserts) each fire the attached
  :class:`~predictionio_tpu.obs.incidents.IncidentRecorder`, whose
  sources capture the merged traces, ring tail, and rollout state at
  that moment (``docs/observability.md``).

Model-rollout admin (``GET /models``, ``POST /models/*``) proxies to one
healthy replica; the change lands in the shared registry and every other
replica adopts it through its registry-sync loop (``docs/fleet.md``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import threading
import time
from typing import Any
from urllib.parse import urlsplit

import aiohttp
from aiohttp import web

from predictionio_tpu.fleet.federation import federate_metrics
from predictionio_tpu.fleet.supervisor import REPLICA_CLASS_CPU
from predictionio_tpu.obs.incidents import IncidentRecorder
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.sampler import HostSampler
from predictionio_tpu.obs.slo import DEFAULT_WINDOWS, SLOEngine
from predictionio_tpu.obs.tsring import TelemetryRing
from predictionio_tpu.obs.tracing import (
    TRACE_HEADER,
    Tracer,
    mint_trace_id,
)
from predictionio_tpu.obs.web import (
    BreakerInstruments,
    OPENMETRICS_CONTENT_TYPE,
    PROMETHEUS_CONTENT_TYPE,
    _wants_exemplars,
    slo_response,
)
from predictionio_tpu.registry.router import routing_key, sticky_bucket
from predictionio_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    OPEN,
    RetryBudget,
)
from predictionio_tpu.tools.top import parse_prometheus

logger = logging.getLogger(__name__)

# forward outcomes that justify trying a different replica: transport
# failures and replica-side 5xx. 4xx is the CLIENT's problem — it would
# fail identically everywhere, and re-dispatching it doubles load for
# nothing.
RETRIABLE_STATUSES = frozenset((500, 502, 503, 504))

# spans fetched per replica per telemetry tick: enough ring to cover a
# probe interval of traffic at fleet scale without the fan-in dominating
# the tick
TRACE_FANIN_LIMIT = 200


@dataclasses.dataclass
class GatewayConfig:
    ip: str = "0.0.0.0"
    port: int = 8000
    replica_urls: tuple[str, ...] = ()
    # /healthz probe cadence and per-probe timeout (ejection latency is
    # bounded by interval + timeout)
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    # per-forward total timeout (connect + response)
    request_timeout_s: float = 10.0
    # one-retry budget: each first attempt earns `ratio` tokens, each
    # retry spends 1 (resilience.RetryBudget semantics)
    retry_budget_ratio: float = 0.2
    # per-replica breaker: consecutive forward failures before the
    # gateway stops routing there without waiting for the next probe
    breaker_threshold: int = 3
    breaker_recovery_s: float = 5.0
    # consistent-hash tie-break key (same field the servers use for
    # sticky canary routing)
    sticky_key_field: str = "user"
    # replica class per replica_urls entry ("device" default); shorter
    # tuples pad with "device". cpu-fallback replicas absorb OVERFLOW
    # only: routed to when every healthy device-class replica already
    # carries >= cpu_overflow_inflight proxied queries (or none is
    # routable) — slower answers instead of sheds, never instead of the
    # fast path (docs/fleet.md §Replica classes)
    replica_classes: tuple[str, ...] = ()
    cpu_overflow_inflight: int = 4
    max_payload_bytes: int = 1 << 20
    shed_retry_after_s: float = 1.0
    drain_grace_s: float = 15.0
    # telemetry tick cadence (federate + SLO + ring append + trace
    # fan-in refresh); None follows probe_interval_s, 0 disables
    telemetry_interval_s: float | None = None
    # fleet SLO burn windows ((seconds, threshold), ...); None = the SRE
    # defaults (300s fast / 3600s slow). Elasticity tests and benches
    # shrink these so post-spike burn decays inside the run instead of
    # pinning the autoscaler's idle detector for five minutes
    slo_windows: tuple[tuple[float, float], ...] | None = None
    # upstream connection pool: keep-alive connections per replica (the
    # proxy hop must not pay a TCP handshake per query) and how long an
    # idle pooled connection survives
    upstream_pool_per_host: int = 32
    upstream_keepalive_s: float = 30.0
    # shared-nothing gateway tier (--gateways N): this gateway's stable
    # id (telemetry-ring writer namespace, peer attribution) and its
    # peers' base URLs for /traces/recent + /slo fan-in. Peers share the
    # replica set behind any TCP balancer; they never share state.
    gateway_id: str = "g0"
    peer_urls: tuple[str, ...] = ()


class Replica:
    """Gateway-side state for one backend QueryServer."""

    def __init__(
        self,
        url: str,
        breaker: CircuitBreaker,
        worker_class: str = "device",
        healthy: bool = True,
    ):
        self.url = url.rstrip("/")
        split = urlsplit(self.url)
        self.name = split.netloc or self.url
        self.worker_class = worker_class
        self.breaker = breaker
        # healthy-until-proven-otherwise: the first probe fires
        # immediately at startup, and the breaker bounds the damage of
        # routing to a replica that was never up. A replica JOINING at
        # runtime (scale-out) is the opposite case — its worker process
        # is still importing jax — so it joins unhealthy and earns
        # routing from its first passing probe.
        self.healthy = healthy
        # a replica that has never passed a probe is "not up yet", not
        # "ejected": startup must not inflate the ejection counter
        self.ever_ready = False
        self.inflight = 0

    def snapshot(self) -> dict[str, Any]:
        return {
            "url": self.url,
            "healthy": self.healthy,
            "workerClass": self.worker_class,
            "inflight": self.inflight,
            "breaker": self.breaker.snapshot(),
        }


class Gateway:
    def __init__(
        self,
        config: GatewayConfig,
        metrics: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
        telemetry: TelemetryRing | None = None,
        incidents: IncidentRecorder | None = None,
    ):
        if not config.replica_urls:
            raise ValueError("gateway needs at least one replica URL")
        self.config = config
        self.metrics = metrics or MetricsRegistry()
        self.tracer = tracer or Tracer(ring_size=512)
        self.telemetry = telemetry
        self.incidents = incidents
        m = self.metrics
        self._breaker_instruments = BreakerInstruments(m)
        # membership funnel: every runtime add/retire mutates the replica
        # set, the breaker map, and the per-replica gauges under this one
        # lock, so the probe loop, routing, and the scrape never see them
        # disagree (docs/fleet.md §Autoscaling)
        self._membership_lock = threading.Lock()
        classes = tuple(config.replica_classes) + ("device",) * max(
            0, len(config.replica_urls) - len(config.replica_classes)
        )
        self.replicas: list[Replica] = []
        for url, worker_class in zip(config.replica_urls, classes):
            self._make_replica(url, worker_class, healthy=True)
        self.retry_budget = RetryBudget(ratio=config.retry_budget_ratio)
        self._m_replicas = m.gauge(
            "pio_fleet_replicas", "replicas configured behind this gateway"
        )
        self._m_replicas.set(len(self.replicas))
        self._m_up = m.gauge(
            "pio_fleet_replica_up",
            "1 when the replica's last /healthz probe passed",
            labelnames=("replica",),
        )
        self._m_inflight = m.gauge(
            "pio_fleet_replica_inflight",
            "queries currently proxied to the replica",
            labelnames=("replica",),
        )
        self._m_requests = m.counter(
            "pio_fleet_requests_total",
            "queries proxied, by replica and upstream status class",
            labelnames=("replica", "status"),
        )
        self._m_ejections = m.counter(
            "pio_fleet_ejections_total",
            "replicas ejected on a failed /healthz probe",
            labelnames=("replica",),
        )
        self._m_readmissions = m.counter(
            "pio_fleet_readmissions_total",
            "ejected replicas readmitted on a passing /healthz probe",
            labelnames=("replica",),
        )
        self._m_retries = m.counter(
            "pio_fleet_retries_total",
            "queries retried on a different replica after a forward failure",
        )
        self._m_no_replica = m.counter(
            "pio_fleet_no_replica_total",
            "queries shed because no routable replica existed",
        )
        self._m_panic = m.counter(
            "pio_fleet_panic_picks_total",
            "queries routed in panic mode: every replica failed its last "
            "probe, so health was ignored (breakers still applied)",
        )
        self._m_overflow = m.counter(
            "pio_fleet_overflow_picks_total",
            "queries routed to a cpu-fallback replica because every "
            "healthy device-class replica was saturated (slower answer "
            "instead of a shed)",
        )
        self._m_membership = m.counter(
            "pio_fleet_membership_changes_total",
            "runtime replica set changes through the membership funnel, "
            "by kind (join/retire)",
            labelnames=("kind",),
        )
        self._m_latency = m.histogram(
            "pio_gateway_request_seconds",
            "gateway e2e proxy wall time (ingress to upstream answer relayed)",
            labelnames=("endpoint",),
        )
        self._m_responses = m.counter(
            "pio_gateway_responses_total",
            "CLIENT-VISIBLE /queries.json outcomes by status class — what "
            "the retry already rescued is a 2xx here (pio_fleet_requests_"
            "total counts the per-attempt forwards)",
            labelnames=("status",),
        )
        self._m_telemetry_snapshots = m.counter(
            "pio_telemetry_snapshots_total",
            "fleet snapshots appended to the on-disk telemetry ring",
        )
        self._m_telemetry_errors = m.counter(
            "pio_telemetry_errors_total",
            "telemetry ticks that failed (federation, SLO, or ring append)",
        )
        self._m_telemetry_records = m.gauge(
            "pio_telemetry_ring_records",
            "records currently live in the telemetry ring (0 when no ring "
            "is attached)",
        )
        m.register_collector(self._collect)
        # fleet-level SLOs over the federated view (obs/slo.py burn-rate
        # engine): snapshots ride the telemetry tick AND the scrape
        self.slo = SLOEngine(m)
        self._last_federated: dict[str, list[tuple[dict[str, str], float]]] = {}
        self._add_fleet_slos()
        m.register_collector(self.slo.collect)
        self._slo_alerting: dict[str, bool] = {}
        # the gateway tier samples its own host threads (event loop +
        # executor pool): GET /profile/stacks answers "is the gateway or
        # the replica slow" without touching a replica
        self.sampler = HostSampler(metrics=m)
        # trace fan-in cache: replica name -> last fetched span dicts.
        # Refreshed per telemetry tick and on /traces/recent; NEVER
        # cleared on fetch failure — a dead replica's final spans are
        # exactly the evidence an incident bundle needs.
        self._replica_spans: dict[str, list[dict[str, Any]]] = {}
        # gateway-peer fan-in cache (--gateways N): peer base url ->
        # spans it served on its LOCAL /traces/recent. Same
        # keep-on-failure rule — a dead peer's last view is evidence.
        self._peer_spans: dict[str, list[dict[str, Any]]] = {}
        self._session: aiohttp.ClientSession | None = None
        self._probe_task: asyncio.Task | None = None
        self._telemetry_task: asyncio.Task | None = None
        self._runner: web.AppRunner | None = None
        self._draining = False
        self._inflight_requests = 0
        # high-water mark since the last telemetry tick: the instant
        # inflight gauge aliases badly under bursty event-loop scheduling
        # (a tick can sample 0 mid-flood); the autoscaler needs "was
        # there concurrency since I last looked", not "at this instant"
        self._inflight_peak = 0
        self._stop_event = asyncio.Event()
        self._drain_task: asyncio.Task | None = None

    # ------------------------------------------------------------- plumbing
    def _collect(self) -> None:
        replicas = self.replicas
        self._m_replicas.set(len(replicas))
        for r in replicas:
            self._m_up.set(1.0 if r.healthy else 0.0, replica=r.name)
            self._m_inflight.set(float(r.inflight), replica=r.name)
        # reconcile-against-live-set (same discipline as pio_ann_index_*):
        # a retired replica's series must not outlive its membership —
        # covers any write that raced the retire funnel
        live = [r.name for r in replicas]
        self._m_up.prune("replica", live)
        self._m_inflight.prune("replica", live)
        state_gauge = self.metrics.get("pio_breaker_state")
        if state_gauge is not None and hasattr(state_gauge, "remove"):
            live_breakers = {r.breaker.name for r in replicas}
            for (bname,), _v in state_gauge.collect():
                if bname.startswith("replica:") and bname not in live_breakers:
                    state_gauge.remove(breaker=bname)
        if self.telemetry is not None:
            self._m_telemetry_records.set(
                float(getattr(self.telemetry, "approx_count", 0))
            )

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            # pooled keep-alive upstream connector: the proxy hop's
            # budget is ~1 ms, a TCP handshake per forward would be most
            # of it. Bounded per replica so one slow backend can't
            # starve the pool fleet-wide; unbounded overall because the
            # replica set itself is the bound.
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(
                    limit=0,
                    limit_per_host=self.config.upstream_pool_per_host,
                    keepalive_timeout=self.config.upstream_keepalive_s,
                ),
                timeout=aiohttp.ClientTimeout(
                    total=self.config.request_timeout_s
                ),
            )
        return self._session

    # ---------------------------------------------------------- fleet SLOs
    def _add_fleet_slos(self) -> None:
        """Fleet-level objectives evaluated over federated counter deltas
        (the replicas' own /slo endpoints rate each process in isolation;
        these rate what CLIENTS of the fleet experience)."""

        def availability() -> tuple[float, float]:
            # CLIENT-VISIBLE outcomes only: a forward that failed and was
            # rescued by the retry is a success here (rating per-attempt
            # forwards would flip this SLO to alerting during a chaos
            # kill whose zero-5xx invariant is actually holding). Sheds
            # are 503 responses, so they are already counted as bad.
            total = bad = 0.0
            for key, v in self._m_responses.collect():
                labels = dict(zip(self._m_responses.labelnames, key))
                total += v
                if labels.get("status") == "5xx":
                    bad += v
            return total, bad

        def latency() -> tuple[float, float]:
            # the paper's <10 ms p50 target, fleet-wide: over-threshold
            # fraction from the FEDERATED request histogram (the
            # replicas' cumulative buckets summed series-wise; 0.01 sits
            # exactly on a ladder bound so good = the 0.01 bucket)
            total = good = 0.0
            for labels, v in self._last_federated.get(
                "pio_request_seconds_bucket", ()
            ):
                if labels.get("endpoint") != "/queries.json":
                    continue
                le = labels.get("le")
                if le == "+Inf":
                    total += v
                elif le == "0.01":
                    good += v
            return total, max(0.0, total - good)

        def shed() -> tuple[float, float]:
            total = sum(v for _key, v in self._m_responses.collect())
            return total, self._m_no_replica.total()

        windows = self.config.slo_windows or DEFAULT_WINDOWS
        self.slo.add(
            "fleet-availability",
            "fraction of fleet queries answered without a 5xx, transport "
            "error, or shed",
            objective=0.999,
            source=availability,
            windows=windows,
        )
        self.slo.add(
            "fleet-latency",
            "fraction of fleet queries under the paper's 10 ms target "
            "(federated replica histograms)",
            objective=0.50,
            source=latency,
            windows=windows,
        )
        self.slo.add(
            "fleet-shed",
            "fraction of fleet queries NOT shed for want of a routable "
            "replica",
            objective=0.99,
            source=shed,
            windows=windows,
        )

    # --------------------------------------------------- incident plumbing
    def _trigger_incident(self, kind: str, context: dict[str, Any]) -> None:
        """Fire the flight recorder WITHOUT stalling the event loop: a
        capture does real disk I/O (ring tail, registry read, bundle
        write), and it fires exactly when the fleet is degraded — the
        worst moment to block every in-flight proxy. Off-loop callers
        fall back to inline capture."""
        if self.incidents is None:
            return
        # profile-on-alert: the incident leaves with the gateway's folded
        # host stacks attached — snapshotted NOW (cheap, in-memory), not
        # on the executor, so the stacks show the moment of the alert
        texts = {"stacks_folded": self.sampler.folded()}
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # pio-lint: disable=async-blocking-call -- RuntimeError branch: no loop is running here, inline capture cannot stall one
            self.incidents.trigger(kind, context=context, texts=texts)
            return
        loop.run_in_executor(
            None,
            lambda: self.incidents.trigger(kind, context=context, texts=texts),
        )

    def _on_breaker_transition(self, name: str, old: str, new: str) -> None:
        if new == OPEN:
            self._trigger_incident(
                "breaker-trip",
                {"breaker": name, "from": old, "to": new},
            )

    def _note_transition(
        self, event: str, replica: Replica, **tags: Any
    ) -> None:
        """The single funnel for replica state transitions: counter +
        health-event span (the eject/readmit timeline incident bundles
        and ``/traces/recent`` replay) — the ``fleet-unattributed-proxy``
        lint rule holds every transition to this path."""
        if event == "eject":
            self._m_ejections.inc(replica=replica.name)
        elif event == "readmit":
            self._m_readmissions.inc(replica=replica.name)
        self.tracer.record_span(
            "gateway.health",
            "gateway",
            0.0,
            trace_id=mint_trace_id(),
            status=event,
            replica=replica.name,
            **tags,
        )

    # ----------------------------------------------------- fleet membership
    def _make_replica(
        self, url: str, worker_class: str, healthy: bool
    ) -> Replica:
        """Construct + register one replica: breaker watched (state
        gauge), trip listener chained (incident trigger), appended to the
        routing set. The only place replicas are born."""
        breaker = self._breaker_instruments.watch(
            CircuitBreaker(
                name=f"replica:{urlsplit(url.rstrip('/')).netloc or url}",
                failure_threshold=self.config.breaker_threshold,
                recovery_timeout_s=self.config.breaker_recovery_s,
            )
        )
        # a breaker tripping OPEN is an incident trigger: by the time an
        # operator looks, the consecutive failures that tripped it are
        # only in the flight recorder
        breaker.chain_listener(self._on_breaker_transition)
        replica = Replica(url, breaker, worker_class=worker_class, healthy=healthy)
        self.replicas = [*self.replicas, replica]
        return replica

    def add_replica(self, url: str, worker_class: str = "device") -> Replica:
        """Scale-out membership: one locked funnel adds the replica to
        the routing set, the breaker map, and the probe loop's view in
        one step. The replica joins UNHEALTHY — no query routes to it
        until its first ``/healthz`` probe passes (a worker paying its
        jax import must not eat traffic)."""
        with self._membership_lock:
            name = urlsplit(url.rstrip("/")).netloc or url
            for r in self.replicas:
                if r.name == name:
                    raise ValueError(f"replica {name!r} already routed")
            replica = self._make_replica(url, worker_class, healthy=False)
            self._m_replicas.set(len(self.replicas))
            self._m_membership.inc(kind="join")
            self._note_transition("join", replica, worker_class=worker_class)
            return replica

    def retire_replica(self, url_or_name: str) -> Replica | None:
        """Scale-in membership: remove the replica from routing through
        the same locked funnel. New requests stop routing to it
        immediately; requests already forwarded hold the Replica object
        and complete normally (the worker drains them after its SIGTERM)
        — the ordering that makes scale-in 5xx-free. Its live-set gauges
        (up/inflight/breaker state) drop from the exposition; its span
        cache is dropped too (a planned retire is not incident
        evidence). Returns the retired replica, or None when unknown."""
        name = urlsplit(url_or_name.rstrip("/")).netloc or url_or_name
        with self._membership_lock:
            victim = next((r for r in self.replicas if r.name == name), None)
            if victim is None:
                return None
            self.replicas = [r for r in self.replicas if r is not victim]
            self._breaker_instruments.unwatch(victim.breaker)
            self._m_up.remove(replica=victim.name)
            self._m_inflight.remove(replica=victim.name)
            self._replica_spans.pop(victim.name, None)
            self._m_replicas.set(len(self.replicas))
            self._m_membership.inc(kind="retire")
            self._note_transition(
                "retire", victim, worker_class=victim.worker_class
            )
            return victim

    def replica_shape(self) -> dict[str, int]:
        """Routable-set census by replica class (the ``gateway`` side of
        the autoscaler's shape; the supervisor's ``live_specs`` is the
        process side)."""
        shape: dict[str, int] = {}
        for r in self.replicas:
            shape[r.worker_class] = shape.get(r.worker_class, 0) + 1
        return shape

    def cached_spans(self) -> list[dict[str, Any]]:
        """Sync merged-trace snapshot (gateway ring + per-tick replica
        caches) — what incident sources capture without touching the
        network mid-incident. Each span is tagged with its ``source``
        tier."""
        out = [
            {**s, "source": "gateway"} for s in self.tracer.recent(None)
        ]
        # list() first: incident captures read this from an executor
        # thread while the telemetry loop mutates the cache on the loop
        for name, spans in list(self._replica_spans.items()):
            out.extend({**s, "source": name} for s in spans)
        out.sort(key=lambda s: s.get("startTime", 0.0), reverse=True)
        return out

    # -------------------------------------------------------------- routing
    def pick_replica(
        self,
        key: str,
        exclude: frozenset[str] = frozenset(),
        meta: dict[str, Any] | None = None,
    ) -> Replica | None:
        """Least-loaded routable replica; consistent-hash tie-break.

        Claims a breaker slot (``allow()``) on the winner — the caller
        MUST pair the pick with ``record_success``/``record_failure``.
        ``meta``, when given, is filled with routing attribution (panic
        mode, healthy count) for the ``gateway.route`` span.
        """
        pool = [r for r in self.replicas if r.name not in exclude]
        candidates = [r for r in pool if r.healthy]
        if meta is not None:
            meta["healthy"] = len(candidates)
        if not candidates and pool:
            # panic routing: EVERY replica failed its last probe. Probes
            # are advisory — one can time out against a loaded-but-alive
            # worker — and when the whole fleet looks down at once, the
            # probes being wrong is likelier than the fleet being dead.
            # Route across all of them; the per-replica breakers still
            # gate backends that are truly gone.
            candidates = pool
            self._m_panic.inc()
            if meta is not None:
                meta["panic"] = True
        if not candidates:
            return None
        chosen = None
        for group in self._class_preference(candidates):
            chosen = self._pick_admitted(group, key)
            if chosen is not None:
                break
        if chosen is None:
            return None
        if chosen.worker_class == REPLICA_CLASS_CPU and any(
            r.worker_class != REPLICA_CLASS_CPU for r in candidates
        ):
            # the device class was saturated (or breaker-refused): this
            # query degrades to a slower cpu-fallback answer, not a shed
            self._m_overflow.inc()
            if meta is not None:
                meta["overflow"] = True
        return chosen

    def _class_preference(self, candidates: list[Replica]) -> list[list[Replica]]:
        """Cost/latency-aware routing order: device-bound replicas carry
        traffic while any has headroom; cpu-fallback replicas absorb
        overflow only; a fully saturated fleet falls back to least-loaded
        across everything (queueing beats shedding)."""
        cpu = [r for r in candidates if r.worker_class == REPLICA_CLASS_CPU]
        device = [r for r in candidates if r.worker_class != REPLICA_CLASS_CPU]
        if not cpu or not device:
            return [candidates]
        thresh = max(1, self.config.cpu_overflow_inflight)
        under_dev = [r for r in device if r.inflight < thresh]
        under_cpu = [r for r in cpu if r.inflight < thresh]
        if under_dev:
            return [g for g in (under_dev, under_cpu, candidates) if g]
        if under_cpu:
            return [under_cpu, candidates]
        return [candidates]

    @staticmethod
    def _pick_admitted(group: list[Replica], key: str) -> Replica | None:
        """Least-loaded within the group, consistent-hash tie-break,
        first replica whose breaker admits the request."""
        if not group:
            return None
        low = min(r.inflight for r in group)
        tied = sorted(
            (r for r in group if r.inflight == low),
            key=lambda r: r.name,
        )
        # rotate the tie list by the sticky hash: same key -> same replica
        # while loads stay equal, different keys spread uniformly
        start = int(sticky_bucket(key) * len(tied)) % len(tied)
        for i in range(len(tied)):
            r = tied[(start + i) % len(tied)]
            try:
                r.breaker.allow()
            except CircuitOpenError:
                continue
            return r
        # every tied replica's breaker refused; try the rest by load
        rest = sorted(
            (r for r in group if r.inflight != low),
            key=lambda r: (r.inflight, r.name),
        )
        for r in rest:
            try:
                r.breaker.allow()
            except CircuitOpenError:
                continue
            return r
        return None

    async def _forward(
        self,
        replica: Replica,
        method: str,
        path: str,
        body: bytes | None,
        headers: dict[str, str],
    ) -> tuple[int, bytes, str]:
        """One proxied request, recorded as a ``gateway.proxy`` span on
        the request's trace id (upstream wall time = span duration).
        Returns (status, body, content_type); raises on transport
        failure. Replica accounting (inflight, breaker, counters) is the
        caller's job — retry logic needs to see the raw outcome."""
        replica.inflight += 1
        t0 = time.perf_counter()
        status: Any = "error"
        try:
            async with self._http().request(
                method, f"{replica.url}{path}", data=body, headers=headers
            ) as resp:
                payload = await resp.read()
                status = resp.status
                return (
                    resp.status,
                    payload,
                    resp.headers.get("Content-Type", "application/json"),
                )
        finally:
            replica.inflight -= 1
            self.tracer.record_span(
                "gateway.proxy",
                "gateway",
                time.perf_counter() - t0,
                trace_id=headers.get(TRACE_HEADER),
                replica=replica.name,
                path=path,
                upstream_status=status,
            )

    @staticmethod
    def _status_class(status: int) -> str:
        return f"{status // 100}xx"

    def _record_outcome(self, replica: Replica, status: int) -> None:
        self._m_requests.inc(
            replica=replica.name, status=self._status_class(status)
        )
        if status in RETRIABLE_STATUSES:
            # replica-side trouble: feeds the breaker like a transport
            # failure (a 503-shedding replica needs backing off from too)
            replica.breaker.record_failure()
        else:
            # 2xx obviously; 4xx too — the *replica* answered fine, the
            # client's request was bad. 4xx must not trip a breaker.
            replica.breaker.record_success()

    # --------------------------------------------------------------- routes
    async def handle_queries(self, request: web.Request) -> web.Response:
        t0 = time.perf_counter()
        resp: web.Response | None = None
        try:
            resp = await self._handle_queries_inner(request)
            return resp
        finally:
            self._m_latency.observe(
                time.perf_counter() - t0, endpoint="/queries.json"
            )
            # client-visible outcome (an escaping exception becomes
            # aiohttp's 500): the fleet-availability SLO's input
            status = resp.status if resp is not None else 500
            self._m_responses.inc(status=self._status_class(status))

    async def _handle_queries_inner(self, request: web.Request) -> web.Response:
        if (
            self.config.max_payload_bytes
            and request.content_length is not None
            and request.content_length > self.config.max_payload_bytes
        ):
            return web.json_response(
                {"message": "query payload too large"}, status=413
            )
        body = await request.read()
        # sticky key for the consistent-hash tie-break; a non-JSON body
        # still routes (the replica will 400 it properly)
        try:
            key = routing_key(json.loads(body), self.config.sticky_key_field)
        except (ValueError, TypeError):
            key = body.decode("utf-8", errors="replace")
        trace_id = request.headers.get(TRACE_HEADER) or mint_trace_id()
        headers = {
            "Content-Type": "application/json",
            TRACE_HEADER: trace_id,
        }
        self._inflight_requests += 1
        if self._inflight_requests > self._inflight_peak:
            self._inflight_peak = self._inflight_requests
        try:
            resp = await self._route_query(key, body, headers, trace_id)
        finally:
            self._inflight_requests -= 1
        resp.headers[TRACE_HEADER] = trace_id
        if resp.status >= 500:
            # the zero-5xx invariant (docs/fleet.md) just broke for a
            # real client: capture the fleet state while the evidence —
            # the dead replica's cached spans, the ring history — is
            # still warm
            self._trigger_incident(
                "fleet-5xx",
                {"status": resp.status, "traceId": trace_id},
            )
        if self._draining:
            # drain keeps ANSWERING: the listener is closed (new
            # connections refused at TCP), but a request arriving on an
            # established keep-alive connection is served — 503ing it
            # would be the 5xx the drain exists to avoid. Connection:
            # close winds the keep-alive down so the client reconnects
            # elsewhere and the drain converges.
            resp.force_close()
        return resp

    async def _route_query(
        self,
        key: str,
        body: bytes,
        headers: dict[str, str],
        trace_id: str,
    ) -> web.Response:
        with self.tracer.span(
            "gateway.route", kind="gateway", trace_id=trace_id
        ) as route_span:
            resp = await self._route_query_inner(
                key, body, headers, route_span
            )
            route_span.tags["status"] = resp.status
            return resp

    async def _route_query_inner(
        self,
        key: str,
        body: bytes,
        headers: dict[str, str],
        route_span: Any,
    ) -> web.Response:
        self.retry_budget.record_attempt()
        pick_meta: dict[str, Any] = {}
        first = self.pick_replica(key, meta=pick_meta)
        route_span.tags.update(pick_meta)
        if first is None:
            self._m_no_replica.inc()
            route_span.tags["shed"] = True
            return self._unavailable(
                "no healthy replica available", self.config.shed_retry_after_s
            )
        route_span.tags["replica"] = first.name
        route_span.tags["breaker"] = first.breaker.snapshot()["state"]
        failure: tuple[int, bytes, str] | None = None
        try:
            status, payload, ctype = await self._forward(
                first, "POST", "/queries.json", body, headers
            )
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            first.breaker.record_failure()
            self._m_requests.inc(replica=first.name, status="error")
            logger.warning("forward to %s failed: %s", first.name, exc)
        else:
            self._record_outcome(first, status)
            if status not in RETRIABLE_STATUSES:
                return web.Response(
                    body=payload, status=status, content_type=_bare(ctype)
                )
            failure = (status, payload, ctype)
        # one retry on a DIFFERENT replica — /queries.json is idempotent
        # (pure read), so re-dispatch cannot double-apply anything
        if self.retry_budget.try_spend():
            retry_meta: dict[str, Any] = {}
            second = self.pick_replica(
                key, exclude=frozenset((first.name,)), meta=retry_meta
            )
            if second is not None:
                self._m_retries.inc()
                route_span.tags["retried"] = True
                route_span.tags["retry_replica"] = second.name
                if retry_meta.get("panic"):
                    route_span.tags["panic"] = True
                try:
                    status, payload, ctype = await self._forward(
                        second, "POST", "/queries.json", body, headers
                    )
                except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                    second.breaker.record_failure()
                    self._m_requests.inc(replica=second.name, status="error")
                    logger.warning(
                        "retry forward to %s failed: %s", second.name, exc
                    )
                else:
                    self._record_outcome(second, status)
                    return web.Response(
                        body=payload, status=status, content_type=_bare(ctype)
                    )
        if failure is not None:
            # relay the replica's own 5xx rather than masking it
            status, payload, ctype = failure
            return web.Response(
                body=payload, status=status, content_type=_bare(ctype)
            )
        return self._unavailable(
            "replica unavailable and retry failed",
            self.config.shed_retry_after_s,
        )

    async def _proxy_admin(
        self, request: web.Request, method: str, path: str
    ) -> web.Response:
        """Single-dispatch proxy for the non-idempotent rollout admin
        surface: exactly ONE replica sees the request (the registry is
        the fan-out — every other replica adopts the state change via
        its sync loop). Never retried: a promote that timed out may
        still have landed."""
        replica = self.pick_replica(path)
        if replica is None:
            return self._unavailable(
                "no healthy replica available", self.config.shed_retry_after_s
            )
        body = await request.read() if request.can_read_body else None
        trace_id = request.headers.get(TRACE_HEADER) or mint_trace_id()
        try:
            status, payload, ctype = await self._forward(
                replica,
                method,
                path,
                body,
                {"Content-Type": "application/json", TRACE_HEADER: trace_id},
            )
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            replica.breaker.record_failure()
            self._m_requests.inc(replica=replica.name, status="error")
            return self._unavailable(
                f"replica {replica.name} unreachable: {exc}",
                self.config.shed_retry_after_s,
            )
        self._record_outcome(replica, status)
        return web.Response(body=payload, status=status, content_type=_bare(ctype))

    async def handle_models(self, request: web.Request) -> web.Response:
        return await self._proxy_admin(request, "GET", "/models")

    async def handle_models_post(self, request: web.Request) -> web.Response:
        action = request.match_info["action"]
        if action not in ("candidate", "promote", "rollback"):
            return web.json_response({"message": "unknown action"}, status=404)
        return await self._proxy_admin(request, "POST", f"/models/{action}")

    async def handle_profile_capture(self, request: web.Request) -> web.Response:
        """Fan a device capture out to exactly ONE replica (the
        single-flight lives server-side; a broadcast would trip every
        replica's 409 rail at once). ``?ms=`` and friends pass through."""
        path = "/profile/capture"
        if request.query_string:
            path += "?" + request.query_string
        return await self._proxy_admin(request, "POST", path)

    async def handle_profile_stacks(self, request: web.Request) -> web.Response:
        """The GATEWAY's own host stacks (folded; ``?format=json`` for
        the structured view) — replica stacks live on each replica's own
        /profile/stacks."""
        if request.query.get("format") == "json":
            body = self.sampler.snapshot()
            body["hotspots"] = self.sampler.hotspots()
            return web.json_response(body)
        return web.Response(
            text=self.sampler.folded(), content_type="text/plain"
        )

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Federated fleet scrape: every reachable replica's /metrics
        merged (counters summed, histogram buckets added) plus the
        gateway's own pio_fleet_* instruments. An OpenMetrics-negotiated
        scrape (Accept or ``?exemplars=1``) federates the replicas'
        exemplar-decorated expositions and carries the clauses through
        the merge — a federated p99 exemplar still resolves to a trace
        id, which ``/traces/recent?trace_id=`` assembles cross-tier."""
        exemplars = _wants_exemplars(request)
        text = await self._federate(exemplars=exemplars)
        return web.Response(
            text=text,
            headers={
                "Content-Type": (
                    OPENMETRICS_CONTENT_TYPE
                    if exemplars
                    else PROMETHEUS_CONTENT_TYPE
                )
            },
        )

    async def _federate(self, exemplars: bool = False) -> str:
        """Fetch + merge the fleet's expositions; refreshes the cached
        federated parse the fleet SLO sources read."""
        texts = [self.metrics.render_prometheus(exemplars=exemplars)]
        results = await asyncio.gather(
            *(self._fetch_metrics(r, exemplars=exemplars) for r in self.replicas)
        )
        texts.extend(t for t in results if t is not None)
        merged = federate_metrics(texts, exemplars=exemplars)
        self._last_federated = parse_prometheus(merged)
        return merged

    async def _fetch_metrics(
        self, replica: Replica, exemplars: bool = False
    ) -> str | None:
        suffix = "?exemplars=1" if exemplars else ""
        try:
            # the telemetry plane's own traffic: this fetch FEEDS
            # federation/the ring; a span per scrape per replica would
            # flood the span ring with the instrument's own data
            # pio-lint: disable=fleet-unattributed-proxy -- telemetry plane fetch
            async with self._http().get(
                f"{replica.url}/metrics{suffix}",
                timeout=aiohttp.ClientTimeout(total=self.config.probe_timeout_s),
            ) as resp:
                if resp.status != 200:
                    return None
                return await resp.text()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return None

    # ----------------------------------------------------- trace fan-in
    async def _fetch_traces(self, replica: Replica) -> None:
        """Refresh one replica's span cache. Failures keep the stale
        cache — a SIGKILLed replica's final spans are incident evidence,
        not staleness."""
        try:
            # fan-in that fills the span cache; tracing the trace fetch
            # would recurse the instrument into its own data
            # pio-lint: disable=fleet-unattributed-proxy -- trace fan-in fetch
            async with self._http().get(
                f"{replica.url}/traces/recent?limit={TRACE_FANIN_LIMIT}",
                timeout=aiohttp.ClientTimeout(total=self.config.probe_timeout_s),
            ) as resp:
                if resp.status != 200:
                    return
                data = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return
        spans = data.get("spans")
        if isinstance(spans, list):
            self._replica_spans[replica.name] = spans

    async def _fetch_peer_traces(self, peer_url: str) -> None:
        """Refresh one gateway peer's span cache from its LOCAL view
        (``?local=1`` stops the fan-in recursing peer->peer->peer).
        Failures keep the stale cache: a lost peer's final spans are the
        gateway-peer-loss evidence, not staleness."""
        try:
            # peer fan-in fetch, same health-plane exemption as _fetch_traces
            # pio-lint: disable=fleet-unattributed-proxy -- gateway-peer trace fan-in
            async with self._http().get(
                f"{peer_url}/traces/recent"
                f"?limit={TRACE_FANIN_LIMIT}&local=1",
                timeout=aiohttp.ClientTimeout(total=self.config.probe_timeout_s),
            ) as resp:
                if resp.status != 200:
                    return
                data = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return
        spans = data.get("spans")
        if isinstance(spans, list):
            self._peer_spans[peer_url] = spans

    def _peer_cached_spans(self) -> list[dict[str, Any]]:
        out: list[dict[str, Any]] = []
        for url, spans in list(self._peer_spans.items()):
            out.extend(
                {**s, "gatewayPeer": url} if "gatewayPeer" not in s else s
                for s in spans
            )
        return out

    async def merged_recent(
        self,
        limit: int = 100,
        trace_id: str | None = None,
        peers: bool = True,
    ) -> list[dict[str, Any]]:
        """The fan-in merged trace view: gateway ring + every replica's,
        refreshed live from healthy replicas (dead ones serve from the
        telemetry tick's cache), plus — in a multi-gateway tier — every
        peer gateway's local view, so one ``/traces/recent`` answers for
        the whole tier no matter which gateway the balancer picked. With
        ``trace_id``, the assembled cross-tier waterfall: that trace's
        spans only, oldest first."""
        fetches = [self._fetch_traces(r) for r in self.replicas if r.healthy]
        if peers:
            fetches += [
                self._fetch_peer_traces(u) for u in self.config.peer_urls
            ]
        await asyncio.gather(*fetches)
        merged = self.cached_spans()
        if peers and self.config.peer_urls:
            # peers also fan in from the shared replica set; drop spans
            # this gateway already holds (same trace id + name + start)
            seen = {
                (s.get("traceId"), s.get("name"), s.get("startTime"))
                for s in merged
            }
            merged += [
                s
                for s in self._peer_cached_spans()
                if (s.get("traceId"), s.get("name"), s.get("startTime"))
                not in seen
            ]
            merged.sort(key=lambda s: s.get("startTime", 0.0), reverse=True)
        if trace_id is not None:
            waterfall = [s for s in merged if s.get("traceId") == trace_id]
            waterfall.sort(key=lambda s: s.get("startTime", 0.0))
            return waterfall
        return merged[: max(0, limit)]

    async def handle_traces(self, request: web.Request) -> web.Response:
        try:
            limit = int(request.query.get("limit", 100))
        except ValueError:
            return web.json_response(
                {"message": "limit must be an integer"}, status=400
            )
        trace_id = request.query.get("trace_id") or None
        local = request.query.get("local") not in (None, "", "0")
        spans = await self.merged_recent(
            limit=limit, trace_id=trace_id, peers=not local
        )
        return web.json_response({"spans": spans})

    # ----------------------------------------------------- telemetry ring
    def fleet_snapshot(self) -> dict[str, Any]:
        """One telemetry-ring record: per-replica state + federated
        counters + SLO burn — the queue-depth/burn/utilization history
        the ROADMAP-2 autoscaler will read."""
        fed = self._last_federated
        counters = {
            key: sum(v for _labels, v in fed.get(name, ()))
            for key, name in (
                ("requests", "pio_fleet_requests_total"),
                ("retries", "pio_fleet_retries_total"),
                ("no_replica", "pio_fleet_no_replica_total"),
                ("panic_picks", "pio_fleet_panic_picks_total"),
                ("overflow_picks", "pio_fleet_overflow_picks_total"),
                # the workers' own admission-control sheds, federated
                ("load_shed", "pio_load_shed_total"),
            )
        }
        counters["errors_5xx"] = sum(
            v
            for labels, v in fed.get("pio_fleet_requests_total", ())
            if labels.get("status") in ("5xx", "error")
        )
        inflight_now = sum(r.inflight for r in self.replicas)
        gauges = {
            "queue_depth": sum(
                v for _labels, v in fed.get("pio_queue_depth", ())
            ),
            "inflight": inflight_now,
            # peak concurrency since the previous TELEMETRY TICK — the
            # alias-proof pressure signal the autoscaler reads. This
            # getter is side-effect-free: incident captures also call it
            # (the 'fleet' evidence source), and a capture mid-spike must
            # not consume the high-water mark out from under the ring
            "inflight_peak": max(self._inflight_peak, inflight_now),
        }
        slo: dict[str, Any] = {}
        for report in self.slo.evaluate():
            slo[report["name"]] = {
                "alerting": report["alerting"],
                "burn": {
                    str(int(w["window_s"])): w["burn_rate"]
                    for w in report["windows"]
                },
            }
        return {
            "kind": "fleet",
            "gateway": self.config.gateway_id,
            "replicas": {
                r.name: {
                    "healthy": r.healthy,
                    "ever_ready": r.ever_ready,
                    "inflight": r.inflight,
                    "class": r.worker_class,
                    "breaker": r.breaker.snapshot()["state"],
                }
                for r in self.replicas
            },
            "shape": self.replica_shape(),
            "counters": counters,
            "gauges": gauges,
            "slo": slo,
        }

    async def _telemetry_tick(self) -> None:
        await self._federate()
        await asyncio.gather(
            *(self._fetch_traces(r) for r in self.replicas if r.healthy)
        )
        self.slo.tick()
        record = self.fleet_snapshot()
        # SLO alert *transitions* trigger the flight recorder (level
        # triggers would re-fire every tick of a long burn; the rate
        # limiter bounds it anyway, but the transition is the incident)
        for name, state in record["slo"].items():
            was = self._slo_alerting.get(name, False)
            now_alerting = bool(state["alerting"])
            self._slo_alerting[name] = now_alerting
            if now_alerting and not was:
                self._trigger_incident("slo-alert", {"slo": name, **state})
        if self.telemetry is not None:
            # ring append is locked file I/O; the ring is thread-safe, so
            # hand it off rather than stall every in-flight proxy
            await asyncio.get_running_loop().run_in_executor(
                None, self.telemetry.append, record
            )
            self._m_telemetry_snapshots.inc()
        # ONLY the telemetry tick consumes the inflight high-water mark
        # (reset to the current level so a sustained plateau stays
        # visible on the next record)
        self._inflight_peak = self._inflight_requests

    async def _telemetry_loop(self) -> None:
        interval = self.config.telemetry_interval_s
        if interval is None:
            interval = self.config.probe_interval_s
        if interval <= 0:
            return
        while True:
            try:
                await self._telemetry_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                self._m_telemetry_errors.inc()
                logger.exception("telemetry tick failed")
            await asyncio.sleep(interval)

    async def handle_telemetry(self, request: web.Request) -> web.Response:
        if self.telemetry is None:
            return web.json_response(
                {"message": "no telemetry ring attached"}, status=404
            )
        try:
            seconds = float(request.query.get("s", 600))
        except ValueError:
            return web.json_response(
                {"message": "s must be a number"}, status=400
            )
        # window() replays on-disk segments (open + json decode); keep the
        # history endpoint off the proxy loop
        records = await asyncio.get_running_loop().run_in_executor(
            None, self.telemetry.window, seconds
        )
        return web.json_response(
            {"windowS": seconds, "records": records}
        )

    async def handle_slo(self, request: web.Request) -> web.Response:
        local = request.query.get("local") not in (None, "", "0")
        if local or not self.config.peer_urls:
            return slo_response(self.slo)
        # multi-gateway tier: each peer rates the traffic the balancer
        # sent IT; the fan-in view answers for the tier from any member.
        # A peer that cannot answer is reported, not hidden — a silent
        # gap here is exactly the balancer-misroute blind spot.
        report = self.slo.report()
        report["gateway"] = self.config.gateway_id
        peers: dict[str, Any] = {}
        for url in self.config.peer_urls:
            try:
                # peer fan-in fetch, same health-plane exemption as the
                # trace fan-in: an SLO scrape is not client traffic
                # pio-lint: disable=fleet-unattributed-proxy -- gateway-peer /slo fan-in
                async with self._http().get(
                    f"{url}/slo?local=1",
                    timeout=aiohttp.ClientTimeout(
                        total=self.config.probe_timeout_s
                    ),
                ) as resp:
                    if resp.status == 200:
                        peers[url] = await resp.json()
                    else:
                        peers[url] = {"error": f"status {resp.status}"}
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as exc:
                peers[url] = {"error": type(exc).__name__}
        report["peers"] = peers
        return web.json_response(report)

    async def handle_healthz(self, request: web.Request) -> web.Response:
        healthy = sum(1 for r in self.replicas if r.healthy)
        ready = healthy > 0 and not self._draining
        return web.json_response(
            {
                "ready": ready,
                "draining": self._draining,
                "replicasHealthy": healthy,
                "replicasTotal": len(self.replicas),
            },
            status=200 if ready else 503,
        )

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "alive",
                "role": "gateway",
                "draining": self._draining,
                "replicas": [r.snapshot() for r in self.replicas],
                "retryBudgetTokens": self.retry_budget.tokens,
            }
        )

    async def handle_stop(self, request: web.Request) -> web.Response:
        self._stop_event.set()
        return web.json_response({"message": "Stopping."})

    @staticmethod
    def _unavailable(message: str, retry_after_s: float) -> web.Response:
        return web.json_response(
            {"message": message},
            status=503,
            headers={"Retry-After": str(max(1, round(retry_after_s)))},
        )

    # ---------------------------------------------------------------- probes
    async def _probe_loop(self) -> None:
        while True:
            try:
                await asyncio.gather(
                    *(self._probe(r) for r in self.replicas)
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("probe pass failed")
            await asyncio.sleep(self.config.probe_interval_s)

    async def _probe(self, replica: Replica) -> None:
        try:
            # probe GETs are the health plane's own traffic (one per
            # replica per second); their OUTCOME transitions route
            # through _note_transition below, which attributes this fn
            async with self._http().get(
                f"{replica.url}/healthz",
                timeout=aiohttp.ClientTimeout(total=self.config.probe_timeout_s),
            ) as resp:
                ok = resp.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            ok = False
        if ok:
            if not replica.healthy:
                replica.healthy = True
                if replica.ever_ready:
                    self._note_transition("readmit", replica)
                    logger.info("replica %s readmitted", replica.name)
                else:
                    self._note_transition("up", replica)
                    logger.info("replica %s up", replica.name)
            replica.ever_ready = True
        elif replica.healthy:
            replica.healthy = False
            if replica.ever_ready:
                self._note_transition("eject", replica)
                logger.warning(
                    "replica %s ejected (failed /healthz)", replica.name
                )
            else:
                logger.info("replica %s not ready yet", replica.name)

    # ------------------------------------------------------------- lifecycle
    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_status),
                web.get("/healthz", self.handle_healthz),
                web.get("/metrics", self.handle_metrics),
                web.get("/slo", self.handle_slo),
                web.get("/traces/recent", self.handle_traces),
                web.get("/telemetry/window", self.handle_telemetry),
                web.post("/queries.json", self.handle_queries),
                web.get("/models", self.handle_models),
                web.post("/models/{action}", self.handle_models_post),
                web.post("/profile/capture", self.handle_profile_capture),
                web.get("/profile/stacks", self.handle_profile_stacks),
                web.post("/stop", self.handle_stop),
            ]
        )

        async def _start_loops(app: web.Application) -> None:
            self.sampler.start()
            self._probe_task = asyncio.ensure_future(self._probe_loop())
            self._telemetry_task = asyncio.ensure_future(
                self._telemetry_loop()
            )

        async def _cleanup(app: web.Application) -> None:
            self.sampler.stop()
            tasks = [self._probe_task, self._telemetry_task]
            self._probe_task = None
            self._telemetry_task = None
            for task in tasks:
                if task is not None:
                    task.cancel()
            await asyncio.gather(
                *(t for t in tasks if t is not None), return_exceptions=True
            )
            if self._session is not None and not self._session.closed:
                await self._session.close()
            self._session = None

        app.on_startup.append(_start_loops)
        app.on_cleanup.append(_cleanup)
        return app

    async def start(self) -> None:
        self._runner = web.AppRunner(self.make_app(), access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.ip, self.config.port)
        await site.start()
        logger.info(
            "fleet gateway on %s:%d (%d replicas)",
            self.config.ip,
            self.config.port,
            len(self.replicas),
        )

    async def drain(self) -> None:
        """Stop accepting, answer in-flight, then return. Idempotent."""
        if self._draining:
            return
        self._draining = True
        logger.info(
            "gateway drain: listener closing, %d in flight",
            self._inflight_requests,
        )
        if self._runner is not None:
            for site in list(self._runner.sites):
                try:
                    await site.stop()
                except Exception:
                    pass
        deadline = time.monotonic() + max(0.0, self.config.drain_grace_s)
        while self._inflight_requests > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if self._inflight_requests:
            logger.warning(
                "gateway drain grace expired with %d requests in flight",
                self._inflight_requests,
            )

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def run_until_stopped(self) -> None:
        await self.start()
        await self._stop_event.wait()
        await self.drain()
        await self.stop()

    def begin_drain(self) -> None:
        """Signal-handler entry: drain, then release run_until_stopped.
        The task is held on its own attribute — the event loop keeps only
        a weak reference, and a GC'd drain task would leave SIGTERM
        hanging forever."""

        async def _go() -> None:
            await self.drain()
            self._stop_event.set()

        self._drain_task = asyncio.ensure_future(_go())


def _bare(content_type: str) -> str:
    """aiohttp's Response(content_type=...) rejects parameters; strip
    ``; charset=...`` from a proxied upstream header."""
    return content_type.split(";", 1)[0].strip() or "application/json"


class GatewayGroup:
    """The autoscaler's view of a multi-gateway tier: membership changes
    (add/retire) fan out to EVERY gateway — all peers route over the
    same replica set, so a scale-out a single gateway learned about
    would silently halve itself behind the balancer. Everything else
    (ring reads, shape) delegates to the primary. Shared-nothing
    otherwise: peers never exchange routing state."""

    def __init__(self, gateways: list[Gateway]):
        if not gateways:
            raise ValueError("GatewayGroup needs at least one gateway")
        self.gateways = list(gateways)
        self.primary = gateways[0]

    def add_replica(self, url: str, worker_class: str = "device") -> None:
        for gw in self.gateways:
            gw.add_replica(url, worker_class)

    def retire_replica(self, url_or_name: str) -> None:
        for gw in self.gateways:
            gw.retire_replica(url_or_name)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.primary, name)


__all__ = [
    "Gateway",
    "GatewayConfig",
    "GatewayGroup",
    "Replica",
    "RETRIABLE_STATUSES",
]
