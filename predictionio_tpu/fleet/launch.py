"""``pio deploy --fleet N`` glue: supervisor + gateway in one process.

Topology: the gateway binds the requested ``--port``; worker i is a
child ``pio deploy`` process on ``port + 1 + i`` bound to localhost
(only the gateway faces traffic). Workers inherit every deploy flag the
operator passed except ``--fleet`` and ``--port``, and get a registry
sync interval so rollout state changes propagate fleet-wide.

SIGTERM to the parent is a zero-downtime stop: the gateway drains
(listener closed, in-flight answered), then the supervisor SIGTERMs the
workers — which drain too (``create_server`` drain path) — escalating
to SIGKILL only past the grace window.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import subprocess
import sys

from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig
from predictionio_tpu.fleet.supervisor import (
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
)
from predictionio_tpu.obs.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

# flags that must not leak from the operator's command line into worker
# argv: the fleet topology flags (value-taking unless noted)
_STRIP_FLAGS = {
    "--fleet": True,
    "--port": True,
    "--ip": True,
    "--fleet-probe-interval": True,
    "--registry-sync-interval": True,
}


def worker_argv(
    cli_argv: list[str],
    port: int,
    sync_interval_s: float,
) -> list[str]:
    """Child process argv for one worker, derived from the parent's CLI
    argv (everything after the program name, i.e. starting at the
    ``deploy`` subcommand). Strips the fleet/port flags (both
    ``--flag value`` and ``--flag=value`` spellings) and appends the
    worker's own port + registry sync cadence."""
    out: list[str] = [sys.executable, "-m", "predictionio_tpu.tools.cli"]
    skip = False
    for arg in cli_argv:
        if skip:
            skip = False
            continue
        flag = arg.split("=", 1)[0]
        if flag in _STRIP_FLAGS:
            skip = _STRIP_FLAGS[flag] and "=" not in arg
            continue
        out.append(arg)
    out += [
        "--ip",
        "127.0.0.1",  # workers face only the gateway
        "--port",
        str(port),
        "--registry-sync-interval",
        str(sync_interval_s),
    ]
    return out


def run_fleet(args, cli_argv: list[str]) -> int:
    """Blocking fleet entry point for ``cmd_deploy``. ``cli_argv`` is the
    raw CLI argument vector (sys.argv[1:]) the workers are derived from."""
    n = int(args.fleet)
    if n < 1:
        raise ValueError("--fleet needs at least 1 replica")
    if getattr(args, "ssl_certfile", None) or getattr(args, "ssl_keyfile", None):
        # workers would inherit the TLS flags and serve HTTPS, but the
        # gateway probes/forwards plain HTTP on loopback — every replica
        # would fail its handshake and the fleet would serve nothing.
        # Terminate TLS in front of the gateway instead.
        raise ValueError(
            "--fleet does not support --ssl-certfile/--ssl-keyfile: workers "
            "bind loopback behind the plain-HTTP gateway; terminate TLS at a "
            "front proxy"
        )
    # None = flag unset (fleet workers default to 1 s); an EXPLICIT 0
    # disables the sync loop, exactly as the help text promises
    sync_arg = getattr(args, "registry_sync_interval", None)
    sync_s = 1.0 if sync_arg is None else float(sync_arg)
    specs = [
        WorkerSpec(name=f"w{i}", port=args.port + 1 + i) for i in range(n)
    ]
    metrics = MetricsRegistry()
    supervisor = Supervisor(
        spawn=lambda spec: subprocess.Popen(
            worker_argv(cli_argv, spec.port, sync_s)
        ),
        specs=specs,
        config=SupervisorConfig(),
        metrics=metrics,
    )
    gateway = Gateway(
        GatewayConfig(
            ip=args.ip,
            port=args.port,
            replica_urls=tuple(s.url for s in specs),
            probe_interval_s=getattr(args, "fleet_probe_interval", 1.0),
            request_timeout_s=args.request_timeout,
            breaker_threshold=args.breaker_threshold,
            breaker_recovery_s=args.breaker_recovery,
            sticky_key_field=args.sticky_key,
        ),
        metrics=metrics,  # one registry: supervisor counters federate too
    )

    async def main() -> None:
        supervisor.start()
        loop = asyncio.get_running_loop()
        sup_task = asyncio.ensure_future(supervisor.run())
        try:
            loop.add_signal_handler(signal.SIGTERM, gateway.begin_drain)
        except (NotImplementedError, RuntimeError):
            pass  # non-POSIX loop: Ctrl-C still stops via KeyboardInterrupt
        try:
            await gateway.run_until_stopped()
        finally:
            sup_task.cancel()
            await asyncio.gather(sup_task, return_exceptions=True)
            # workers drain on SIGTERM (create_server drain path); the
            # supervisor escalates to SIGKILL only past the grace window
            await loop.run_in_executor(None, supervisor.stop)

    print(
        f"Fleet gateway starting on {args.ip}:{args.port} "
        f"({n} workers on ports {specs[0].port}-{specs[-1].port}) ..."
    )
    asyncio.run(main())
    return 0


__all__ = ["run_fleet", "worker_argv"]
