"""``pio deploy --fleet N`` glue: supervisor + gateway in one process.

Topology: the gateway binds the requested ``--port``; worker i is a
child ``pio deploy`` process on ``port + 1 + i`` bound to localhost
(only the gateway faces traffic). Workers inherit every deploy flag the
operator passed except ``--fleet`` and ``--port``, and get a registry
sync interval so rollout state changes propagate fleet-wide.

SIGTERM to the parent is a zero-downtime stop: the gateway drains
(listener closed, in-flight answered), then the supervisor SIGTERMs the
workers — which drain too (``create_server`` drain path) — escalating
to SIGKILL only past the grace window.

The fleet observability plane (``--obs-dir``, default ``pio_obs``) also
lives here: worker stderr/stdout captured into per-replica rotating tail
files (:mod:`.worklog`), a durable telemetry ring the gateway appends
fleet snapshots into (:mod:`obs.tsring`), and the incident flight
recorder (:mod:`obs.incidents`) whose sources — merged traces, ring
tail, supervisor ladder, registry state — are wired up so a worker
crash, breaker trip, or fleet SLO alert leaves an inspectable bundle
(``pio incidents list``).
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import subprocess
import sys

from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig
from predictionio_tpu.fleet.supervisor import (
    REPLICA_CLASS_CPU,
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
)
from predictionio_tpu.fleet.worklog import WorkerLogBook, spawn_with_log
from predictionio_tpu.obs.incidents import IncidentRecorder
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tsring import TelemetryRing

logger = logging.getLogger(__name__)

# flags that must not leak from the operator's command line into worker
# argv: the fleet topology flags (value-taking unless noted)
_STRIP_FLAGS = {
    "--fleet": True,
    "--port": True,
    "--ip": True,
    "--fleet-probe-interval": True,
    "--registry-sync-interval": True,
    "--obs-dir": True,
    # elasticity flags are parent-only too (a worker recursively
    # autoscaling would be a fork bomb with extra steps)
    "--autoscale": False,
    "--fleet-min": True,
    "--fleet-max": True,
    "--cpu-fallback-max": True,
    "--autoscale-interval": True,
}


def worker_argv(
    cli_argv: list[str],
    port: int,
    sync_interval_s: float,
) -> list[str]:
    """Child process argv for one worker, derived from the parent's CLI
    argv (everything after the program name, i.e. starting at the
    ``deploy`` subcommand). Strips the fleet/port flags (both
    ``--flag value`` and ``--flag=value`` spellings) and appends the
    worker's own port + registry sync cadence."""
    out: list[str] = [sys.executable, "-m", "predictionio_tpu.tools.cli"]
    skip = False
    for arg in cli_argv:
        if skip:
            skip = False
            continue
        flag = arg.split("=", 1)[0]
        if flag in _STRIP_FLAGS:
            skip = _STRIP_FLAGS[flag] and "=" not in arg
            continue
        out.append(arg)
    out += [
        "--ip",
        "127.0.0.1",  # workers face only the gateway
        "--port",
        str(port),
        "--registry-sync-interval",
        str(sync_interval_s),
    ]
    return out


def run_fleet(args, cli_argv: list[str]) -> int:
    """Blocking fleet entry point for ``cmd_deploy``. ``cli_argv`` is the
    raw CLI argument vector (sys.argv[1:]) the workers are derived from."""
    n = int(args.fleet)
    if n < 1:
        raise ValueError("--fleet needs at least 1 replica")
    if getattr(args, "ssl_certfile", None) or getattr(args, "ssl_keyfile", None):
        # workers would inherit the TLS flags and serve HTTPS, but the
        # gateway probes/forwards plain HTTP on loopback — every replica
        # would fail its handshake and the fleet would serve nothing.
        # Terminate TLS in front of the gateway instead.
        raise ValueError(
            "--fleet does not support --ssl-certfile/--ssl-keyfile: workers "
            "bind loopback behind the plain-HTTP gateway; terminate TLS at a "
            "front proxy"
        )
    # None = flag unset (fleet workers default to 1 s); an EXPLICIT 0
    # disables the sync loop, exactly as the help text promises
    sync_arg = getattr(args, "registry_sync_interval", None)
    sync_s = 1.0 if sync_arg is None else float(sync_arg)
    specs = [
        WorkerSpec(name=f"w{i}", port=args.port + 1 + i) for i in range(n)
    ]
    metrics = MetricsRegistry()
    obs = build_obs_plane(
        getattr(args, "obs_dir", "pio_obs"),
        metrics,
        registry_dir=getattr(args, "registry_dir", None),
    )
    logbook = obs.get("logbook")

    # scale-out slot allocator: names/ports after the boot-time range,
    # monotonic so a retired slot is never reused while its old process
    # could still be draining
    next_slot = [n]

    def spec_factory(worker_class: str) -> WorkerSpec:
        i = next_slot[0]
        next_slot[0] += 1
        prefix = "c" if worker_class == REPLICA_CLASS_CPU else "w"
        return WorkerSpec(
            name=f"{prefix}{i}",
            port=args.port + 1 + i,
            worker_class=worker_class,
        )

    def spawn(spec: WorkerSpec):
        argv = worker_argv(cli_argv, spec.port, sync_s)
        env = None
        if spec.worker_class == REPLICA_CLASS_CPU:
            # the cpu-fallback class IS the cheap tier: same server
            # stack, CPU backend — overflow degrades to slower answers
            # instead of competing for the accelerator
            env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        if logbook is not None:
            return spawn_with_log(argv, logbook, spec.name, env=env)
        return subprocess.Popen(argv, env=env)

    supervisor = Supervisor(
        spawn=spawn,
        specs=specs,
        config=SupervisorConfig(),
        metrics=metrics,
        logbook=logbook,
        on_crash=obs.get("on_crash"),
    )
    gateway = Gateway(
        GatewayConfig(
            ip=args.ip,
            port=args.port,
            replica_urls=tuple(s.url for s in specs),
            probe_interval_s=getattr(args, "fleet_probe_interval", 1.0),
            request_timeout_s=args.request_timeout,
            breaker_threshold=args.breaker_threshold,
            breaker_recovery_s=args.breaker_recovery,
            sticky_key_field=args.sticky_key,
        ),
        metrics=metrics,  # one registry: supervisor counters federate too
        telemetry=obs.get("telemetry"),
        incidents=obs.get("incidents"),
    )
    wire_incident_sources(obs.get("incidents"), gateway, supervisor)

    autoscaler = None
    if getattr(args, "autoscale", False):
        ring = obs.get("telemetry")
        if ring is None:
            raise ValueError(
                "--autoscale reads the telemetry ring; it cannot run with "
                "the flight recorder disabled (--obs-dir '')"
            )
        autoscaler = build_autoscaler(
            args, supervisor, gateway, spec_factory, ring, metrics, obs
        )

    async def main() -> None:
        supervisor.start()
        loop = asyncio.get_running_loop()
        sup_task = asyncio.ensure_future(supervisor.run())
        auto_task = (
            asyncio.ensure_future(autoscaler.run())
            if autoscaler is not None
            else None
        )
        try:
            loop.add_signal_handler(signal.SIGTERM, gateway.begin_drain)
        except (NotImplementedError, RuntimeError):
            pass  # non-POSIX loop: Ctrl-C still stops via KeyboardInterrupt
        try:
            await gateway.run_until_stopped()
        finally:
            tasks = [t for t in (sup_task, auto_task) if t is not None]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            # workers drain on SIGTERM (create_server drain path); the
            # supervisor escalates to SIGKILL only past the grace window
            await loop.run_in_executor(None, supervisor.stop)

    print(
        f"Fleet gateway starting on {args.ip}:{args.port} "
        f"({n} workers on ports {specs[0].port}-{specs[-1].port}) ..."
    )
    if obs.get("dir"):
        print(
            f"Fleet flight recorder in {obs['dir']} "
            "(telemetry ring, worker logs, incident bundles; "
            "`pio incidents list`, `pio top --history`)"
        )
    if autoscaler is not None:
        cfg = autoscaler.policy.config
        print(
            f"Autoscaler on: device envelope [{cfg.min_replicas}.."
            f"{cfg.max_replicas}], cpu-fallback max {cfg.cpu_fallback_max}, "
            f"tick {cfg.tick_interval_s:g}s (docs/fleet.md §Autoscaling)"
        )
    try:
        asyncio.run(main())
    finally:
        ring = obs.get("telemetry")
        if ring is not None:
            ring.close()
    return 0


def build_autoscaler(
    args,
    supervisor: Supervisor,
    gateway: Gateway,
    spec_factory,
    ring,
    metrics: MetricsRegistry,
    obs: dict,
):
    """Assemble the elasticity loop from the deploy flags: policy
    envelope (``--fleet-min/--fleet-max/--cpu-fallback-max``), the
    telemetry ring as the single signal path, the registry as the
    mid-bake gate, and the incident recorder for envelope saturation."""
    from predictionio_tpu.fleet.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        ScalingPolicy,
        registry_rollout_probe,
    )

    n = int(args.fleet)

    def flag(name, default, cast):
        # None = unset -> default; an EXPLICIT value is honored verbatim
        # and validated below (`or` would silently turn an explicit 0
        # into the default — the unset-vs-zero bug PR 9 fixed for
        # --registry-sync-interval)
        value = getattr(args, name, None)
        return default if value is None else cast(value)

    config = AutoscalerConfig(
        min_replicas=flag("fleet_min", 1, int),
        # default headroom: twice the boot size (an envelope equal to N
        # would make --autoscale a no-op outward)
        max_replicas=flag("fleet_max", max(1, 2 * n), int),
        cpu_fallback_max=flag("cpu_fallback_max", 0, int),
        tick_interval_s=flag("autoscale_interval", 5.0, float),
    )
    if config.min_replicas < 1:
        raise ValueError("--fleet-min must be >= 1 (0 would drain the fleet)")
    if config.min_replicas > config.max_replicas:
        raise ValueError("--fleet-min cannot exceed --fleet-max")
    if config.max_replicas < n:
        # booting above the ceiling would pin every pressured tick on
        # "saturated" (bundle spam) while the operator believes the
        # envelope bounds the fleet
        raise ValueError(
            f"--fleet-max ({config.max_replicas}) must be >= the --fleet "
            f"boot size ({n})"
        )
    if config.cpu_fallback_max < 0:
        raise ValueError("--cpu-fallback-max must be >= 0")
    if config.tick_interval_s <= 0:
        raise ValueError("--autoscale-interval must be > 0")
    registry_dir = getattr(args, "registry_dir", None)
    return Autoscaler(
        ScalingPolicy(config),
        supervisor,
        gateway,
        spec_factory,
        ring=ring,
        rollout_probe=(
            registry_rollout_probe(registry_dir) if registry_dir else None
        ),
        metrics=metrics,
        incidents=obs.get("incidents"),
    )


def build_obs_plane(
    obs_dir: str | None,
    metrics: MetricsRegistry,
    registry_dir: str | None = None,
) -> dict:
    """The fleet flight-recorder wiring: worker logbook, telemetry ring,
    incident recorder (all under ``obs_dir``; empty/None disables).
    Returns the pieces keyed by role plus the supervisor ``on_crash``
    hook. Split out of :func:`run_fleet` so tests and the chaos e2e can
    assemble the identical plane around in-process fleets."""
    if not obs_dir:
        return {}
    obs_dir = os.path.abspath(obs_dir)
    logbook = WorkerLogBook(os.path.join(obs_dir, "logs"))
    telemetry = TelemetryRing(os.path.join(obs_dir, "telemetry"))
    incidents = IncidentRecorder(
        os.path.join(obs_dir, "incidents"), metrics=metrics
    )
    if registry_dir:

        def registry_state() -> dict:
            # lazy import: the launcher must not pay the registry import
            # unless an incident actually captures
            from predictionio_tpu.registry.store import ArtifactStore

            store = ArtifactStore(registry_dir)
            out: dict = {}
            for engine_key in store.engines():
                state = store.state_by_key(engine_key)
                out[engine_key] = {
                    "generation": state.generation,
                    "stable": state.stable,
                    "candidate": state.candidate,
                    "mode": state.mode,
                    "fraction": state.fraction,
                }
            return out

        incidents.add_source("registry", registry_state)
    incidents.add_source(
        "telemetry", lambda: telemetry.tail(120)
    )

    def on_crash(info: dict) -> None:
        texts = {}
        tail = info.pop("stderrTail", None)
        if tail:
            texts["stderr_tail"] = tail
        incidents.trigger(
            "worker-park" if info.get("parked") else "worker-crash",
            context=info,
            texts=texts,
        )

    return {
        "dir": obs_dir,
        "logbook": logbook,
        "telemetry": telemetry,
        "incidents": incidents,
        "on_crash": on_crash,
    }


def wire_incident_sources(
    incidents, gateway: Gateway, supervisor: Supervisor
) -> None:
    """Attach the live-state evidence sources once both tiers exist: the
    gateway's merged trace snapshot (its own ring + the per-tick replica
    caches — a SIGKILLed worker's final spans survive in the cache) and
    the supervisor's restart ladder."""
    if incidents is None:
        return
    incidents.add_source("traces", lambda: gateway.cached_spans()[:400])
    incidents.add_source("fleet", gateway.fleet_snapshot)
    incidents.add_source("supervisor", supervisor.snapshot)


__all__ = [
    "build_autoscaler",
    "build_obs_plane",
    "run_fleet",
    "wire_incident_sources",
    "worker_argv",
]
