"""``pio deploy --fleet N`` glue: supervisor + gateway in one process.

Topology: the gateway binds the requested ``--port``; worker i is a
child ``pio deploy`` process on ``port + 1 + i`` bound to localhost
(only the gateway faces traffic). Workers inherit every deploy flag the
operator passed except ``--fleet`` and ``--port``, and get a registry
sync interval so rollout state changes propagate fleet-wide.

SIGTERM to the parent is a zero-downtime stop: the gateway drains
(listener closed, in-flight answered), then the supervisor SIGTERMs the
workers — which drain too (``create_server`` drain path) — escalating
to SIGKILL only past the grace window.

The fleet observability plane (``--obs-dir``, default ``pio_obs``) also
lives here: worker stderr/stdout captured into per-replica rotating tail
files (:mod:`.worklog`), a durable telemetry ring the gateway appends
fleet snapshots into (:mod:`obs.tsring`), and the incident flight
recorder (:mod:`obs.incidents`) whose sources — merged traces, ring
tail, supervisor ladder, registry state — are wired up so a worker
crash, breaker trip, or fleet SLO alert leaves an inspectable bundle
(``pio incidents list``).
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import subprocess
import sys

from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig, GatewayGroup
from predictionio_tpu.fleet.hostrt import (
    DRIVER_CONTAINER,
    DRIVER_SSH,
    HostRuntime,
    assign_hosts,
    parse_hosts,
)
from predictionio_tpu.fleet.supervisor import (
    REPLICA_CLASS_CPU,
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
)
from predictionio_tpu.fleet.worklog import WorkerLogBook, spawn_with_log
from predictionio_tpu.obs.incidents import IncidentRecorder
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tsring import TelemetryRing

logger = logging.getLogger(__name__)

# flags that must not leak from the operator's command line into worker
# argv: the fleet topology flags (value-taking unless noted)
_STRIP_FLAGS = {
    "--fleet": True,
    "--port": True,
    "--ip": True,
    "--fleet-probe-interval": True,
    "--registry-sync-interval": True,
    "--obs-dir": True,
    # elasticity flags are parent-only too (a worker recursively
    # autoscaling would be a fork bomb with extra steps)
    "--autoscale": False,
    "--fleet-min": True,
    "--fleet-max": True,
    "--cpu-fallback-max": True,
    "--autoscale-interval": True,
    # multi-host / multi-gateway topology flags are parent-only too
    "--hosts": True,
    "--gateways": True,
    # the lifecycle controller lives in the fleet parent only (a worker
    # running its own retune loop would grid-search once per replica)
    "--lifecycle": True,
    "--lifecycle-cadence": True,
    "--lifecycle-cooldown": True,
    "--lifecycle-workers": True,
    "--lifecycle-nice": True,
    "--lifecycle-warm-limit": True,
    "--lifecycle-app": True,
}


def worker_argv(
    cli_argv: list[str],
    port: int,
    sync_interval_s: float,
    bind_ip: str = "127.0.0.1",
) -> list[str]:
    """Child process argv for one worker, derived from the parent's CLI
    argv (everything after the program name, i.e. starting at the
    ``deploy`` subcommand). Strips the fleet/port flags (both
    ``--flag value`` and ``--flag=value`` spellings) and appends the
    worker's own port + registry sync cadence. Workers on a REMOTE host
    bind all interfaces (the gateway dials them across the wire);
    same-box workers stay loopback-only."""
    out: list[str] = [sys.executable, "-m", "predictionio_tpu.tools.cli"]
    skip = False
    for arg in cli_argv:
        if skip:
            skip = False
            continue
        flag = arg.split("=", 1)[0]
        if flag in _STRIP_FLAGS:
            skip = _STRIP_FLAGS[flag] and "=" not in arg
            continue
        out.append(arg)
    out += [
        "--ip",
        bind_ip,
        "--port",
        str(port),
        "--registry-sync-interval",
        str(sync_interval_s),
    ]
    return out


def run_fleet(args, cli_argv: list[str]) -> int:
    """Blocking fleet entry point for ``cmd_deploy``. ``cli_argv`` is the
    raw CLI argument vector (sys.argv[1:]) the workers are derived from."""
    n = int(args.fleet)
    if n < 1:
        raise ValueError("--fleet needs at least 1 replica")
    if getattr(args, "ssl_certfile", None) or getattr(args, "ssl_keyfile", None):
        # workers would inherit the TLS flags and serve HTTPS, but the
        # gateway probes/forwards plain HTTP on loopback — every replica
        # would fail its handshake and the fleet would serve nothing.
        # Terminate TLS in front of the gateway instead.
        raise ValueError(
            "--fleet does not support --ssl-certfile/--ssl-keyfile: workers "
            "bind loopback behind the plain-HTTP gateway; terminate TLS at a "
            "front proxy"
        )
    # None = flag unset (fleet workers default to 1 s); an EXPLICIT 0
    # disables the sync loop, exactly as the help text promises
    sync_arg = getattr(args, "registry_sync_interval", None)
    sync_s = 1.0 if sync_arg is None else float(sync_arg)
    n_gateways = int(getattr(args, "gateways", 1) or 1)
    if n_gateways < 1:
        raise ValueError("--gateways needs at least 1 gateway")
    metrics = MetricsRegistry()
    obs = build_obs_plane(
        getattr(args, "obs_dir", "pio_obs"),
        metrics,
        registry_dir=getattr(args, "registry_dir", None),
    )
    logbook = obs.get("logbook")

    # host inventory (--hosts): the declared boxes workers place across;
    # unset collapses to the classic single-box deploy (no runtime, no
    # probes — byte-for-byte the pre-multi-host behavior)
    hosts_arg = getattr(args, "hosts", None)
    runtime = None
    if hosts_arg:
        host_specs = parse_hosts(hosts_arg)
        runtime = HostRuntime(host_specs, logbook=logbook)
        placement = assign_hosts(n, host_specs)
        specs = [
            WorkerSpec(
                name=f"w{i}",
                port=args.port + n_gateways + i,
                host=placement[i],
                addr=runtime.host(placement[i]).connect_ip,
            )
            for i in range(n)
        ]
    else:
        # gateways occupy ports base..base+G-1; workers follow. With the
        # default single gateway that is exactly the old port+1+i scheme.
        specs = [
            WorkerSpec(name=f"w{i}", port=args.port + n_gateways + i)
            for i in range(n)
        ]

    def spawn(spec: WorkerSpec):
        cpu = spec.worker_class == REPLICA_CLASS_CPU
        if runtime is not None:
            host = runtime.host(spec.host)
            remote = host.driver in (DRIVER_SSH, DRIVER_CONTAINER)
            argv = worker_argv(
                cli_argv,
                spec.port,
                sync_s,
                bind_ip="0.0.0.0" if remote else "127.0.0.1",
            )
            if remote:
                # remote spawns export ONLY what the worker needs; the
                # parent's whole environment does not belong on the wire
                env = {"JAX_PLATFORMS": "cpu"} if cpu else None
            else:
                env = {**os.environ, "JAX_PLATFORMS": "cpu"} if cpu else None
            return runtime.spawn_worker(spec.host, spec.name, argv, env)
        argv = worker_argv(cli_argv, spec.port, sync_s)
        env = None
        if cpu:
            # the cpu-fallback class IS the cheap tier: same server
            # stack, CPU backend — overflow degrades to slower answers
            # instead of competing for the accelerator
            env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        if logbook is not None:
            return spawn_with_log(argv, logbook, spec.name, env=env)
        return subprocess.Popen(argv, env=env)

    def on_host_down(info: dict) -> None:
        # ONE bundle per host death (the supervisor already folded every
        # resident worker into this single transition); each dead
        # worker's log tail lands as its own text part
        incidents = obs.get("incidents")
        if incidents is None:
            return
        texts = {}
        for winfo in info.get("workers", []):
            tail = winfo.pop("logTail", "")
            if tail:
                texts[f"log_tail_{winfo['replica']}"] = tail
        incidents.trigger("host-death", context=info, texts=texts)

    supervisor = Supervisor(
        spawn=spawn,
        specs=specs,
        config=SupervisorConfig(),
        metrics=metrics,
        logbook=logbook,
        on_crash=obs.get("on_crash"),
        runtime=runtime,
        on_host_down=on_host_down,
    )

    # scale-out slot allocator: names/ports after the boot-time range,
    # monotonic so a retired slot is never reused while its old process
    # could still be draining. Placement is host-aware: the supervisor
    # picks the UP host with the most free slots (how the autoscaler
    # restores capacity on the survivor after a host death).
    next_slot = [n]

    def spec_factory(worker_class: str) -> WorkerSpec:
        i = next_slot[0]
        next_slot[0] += 1
        prefix = "c" if worker_class == REPLICA_CLASS_CPU else "w"
        kw = {}
        if runtime is not None:
            host = supervisor.pick_host()
            if host is None:
                raise RuntimeError(
                    "scale-out wanted but no live host has a free slot "
                    "(grow --hosts)"
                )
            kw = {"host": host, "addr": runtime.host(host).connect_ip}
        return WorkerSpec(
            name=f"{prefix}{i}",
            port=args.port + n_gateways + i,
            worker_class=worker_class,
            **kw,
        )

    gateways: list[Gateway] = []
    rings = [obs.get("telemetry")]
    for g in range(n_gateways):
        if g == 0:
            ring_g = obs.get("telemetry")
        elif obs.get("dir"):
            # peer gateways write the SAME ring directory under their own
            # writer namespace — never interleaving a segment file
            ring_g = TelemetryRing(
                os.path.join(obs["dir"], "telemetry"), writer_id=f"g{g}"
            )
            rings.append(ring_g)
        else:
            ring_g = None
        gateways.append(
            Gateway(
                GatewayConfig(
                    ip=args.ip,
                    port=args.port + g,
                    replica_urls=tuple(s.url for s in specs),
                    probe_interval_s=getattr(args, "fleet_probe_interval", 1.0),
                    request_timeout_s=args.request_timeout,
                    breaker_threshold=args.breaker_threshold,
                    breaker_recovery_s=args.breaker_recovery,
                    sticky_key_field=args.sticky_key,
                    gateway_id=f"g{g}",
                    peer_urls=tuple(
                        f"http://127.0.0.1:{args.port + p}"
                        for p in range(n_gateways)
                        if p != g
                    ),
                ),
                # one registry for the primary: supervisor counters
                # federate through it exactly as before. Peers are
                # shared-nothing — their own registries, their own
                # /metrics (the balancer's scrape view per member).
                metrics=metrics if g == 0 else MetricsRegistry(),
                telemetry=ring_g,
                incidents=obs.get("incidents") if g == 0 else None,
            )
        )
    gateway = gateways[0]
    wire_incident_sources(obs.get("incidents"), gateway, supervisor)

    autoscaler = None
    if getattr(args, "autoscale", False):
        ring = obs.get("telemetry")
        if ring is None:
            raise ValueError(
                "--autoscale reads the telemetry ring; it cannot run with "
                "the flight recorder disabled (--obs-dir '')"
            )
        # membership changes (add/retire) must land on EVERY gateway —
        # the group fans those two calls out and reads from the primary
        scale_target = (
            GatewayGroup(gateways) if len(gateways) > 1 else gateway
        )
        autoscaler = build_autoscaler(
            args, supervisor, scale_target, spec_factory, ring, metrics, obs
        )

    lifecycle = None
    if getattr(args, "lifecycle", None):
        if obs.get("telemetry") is None:
            raise ValueError(
                "--lifecycle reads drift signals off the telemetry ring; "
                "it cannot run with the flight recorder disabled "
                "(--obs-dir '')"
            )
        lifecycle = build_lifecycle(
            args, metrics, obs, serve_url=f"http://127.0.0.1:{args.port}"
        )

    async def main() -> None:
        supervisor.start()
        loop = asyncio.get_running_loop()
        sup_task = asyncio.ensure_future(supervisor.run())
        auto_task = (
            asyncio.ensure_future(autoscaler.run())
            if autoscaler is not None
            else None
        )
        life_task = (
            asyncio.ensure_future(lifecycle.run())
            if lifecycle is not None
            else None
        )

        def drain_all() -> None:
            for gw in gateways:
                gw.begin_drain()

        try:
            loop.add_signal_handler(signal.SIGTERM, drain_all)
        except (NotImplementedError, RuntimeError):
            pass  # non-POSIX loop: Ctrl-C still stops via KeyboardInterrupt
        # peers first (g1..gN-1 on port+1..), then the primary's serve
        # loop blocks until drain; each peer is its own shared-nothing
        # listener over the identical replica set
        for gw in gateways[1:]:
            await gw.start()
        try:
            await gateway.run_until_stopped()
        finally:
            for gw in gateways[1:]:
                try:
                    await gw.stop()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    logger.exception("peer gateway stop failed")
            tasks = [
                t for t in (sup_task, auto_task, life_task) if t is not None
            ]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            # workers drain on SIGTERM (create_server drain path); the
            # supervisor escalates to SIGKILL only past the grace window
            await loop.run_in_executor(None, supervisor.stop)

    if n_gateways > 1:
        print(
            f"Fleet gateways starting on {args.ip}:{args.port}-"
            f"{args.port + n_gateways - 1} ({n_gateways} shared-nothing "
            f"listeners; put any TCP balancer in front) "
            f"({n} workers on ports {specs[0].port}-{specs[-1].port}) ..."
        )
    else:
        print(
            f"Fleet gateway starting on {args.ip}:{args.port} "
            f"({n} workers on ports {specs[0].port}-{specs[-1].port}) ..."
        )
    if runtime is not None:
        census = ", ".join(
            f"{h.name}[{h.driver}]x{h.slots}" for h in runtime.hosts()
        )
        print(f"Host inventory: {census} (docs/fleet.md §Multi-host)")
    if obs.get("dir"):
        print(
            f"Fleet flight recorder in {obs['dir']} "
            "(telemetry ring, worker logs, incident bundles; "
            "`pio incidents list`, `pio top --history`)"
        )
    if autoscaler is not None:
        cfg = autoscaler.policy.config
        print(
            f"Autoscaler on: device envelope [{cfg.min_replicas}.."
            f"{cfg.max_replicas}], cpu-fallback max {cfg.cpu_fallback_max}, "
            f"tick {cfg.tick_interval_s:g}s (docs/fleet.md §Autoscaling)"
        )
    if lifecycle is not None:
        lcfg = lifecycle.policy.config
        triggers = (
            f"drift + cadence {lcfg.cadence_s:g}s"
            if lcfg.cadence_s
            else "drift/manual"
        )
        print(
            f"Lifecycle controller on: {triggers}, state "
            f"{lifecycle.state_dir} (`pio lifecycle status`, "
            "docs/lifecycle.md)"
        )
    try:
        asyncio.run(main())
    finally:
        for ring in rings:
            if ring is not None:
                ring.close()
    return 0


def build_autoscaler(
    args,
    supervisor: Supervisor,
    gateway: Gateway,
    spec_factory,
    ring,
    metrics: MetricsRegistry,
    obs: dict,
):
    """Assemble the elasticity loop from the deploy flags: policy
    envelope (``--fleet-min/--fleet-max/--cpu-fallback-max``), the
    telemetry ring as the single signal path, the registry as the
    mid-bake gate, and the incident recorder for envelope saturation."""
    from predictionio_tpu.fleet.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        ScalingPolicy,
        registry_rollout_probe,
    )

    n = int(args.fleet)

    def flag(name, default, cast):
        # None = unset -> default; an EXPLICIT value is honored verbatim
        # and validated below (`or` would silently turn an explicit 0
        # into the default — the unset-vs-zero bug PR 9 fixed for
        # --registry-sync-interval)
        value = getattr(args, name, None)
        return default if value is None else cast(value)

    config = AutoscalerConfig(
        min_replicas=flag("fleet_min", 1, int),
        # default headroom: twice the boot size (an envelope equal to N
        # would make --autoscale a no-op outward)
        max_replicas=flag("fleet_max", max(1, 2 * n), int),
        cpu_fallback_max=flag("cpu_fallback_max", 0, int),
        tick_interval_s=flag("autoscale_interval", 5.0, float),
    )
    if config.min_replicas < 1:
        raise ValueError("--fleet-min must be >= 1 (0 would drain the fleet)")
    if config.min_replicas > config.max_replicas:
        raise ValueError("--fleet-min cannot exceed --fleet-max")
    if config.max_replicas < n:
        # booting above the ceiling would pin every pressured tick on
        # "saturated" (bundle spam) while the operator believes the
        # envelope bounds the fleet
        raise ValueError(
            f"--fleet-max ({config.max_replicas}) must be >= the --fleet "
            f"boot size ({n})"
        )
    if config.cpu_fallback_max < 0:
        raise ValueError("--cpu-fallback-max must be >= 0")
    if config.tick_interval_s <= 0:
        raise ValueError("--autoscale-interval must be > 0")
    registry_dir = getattr(args, "registry_dir", None)
    return Autoscaler(
        ScalingPolicy(config),
        supervisor,
        gateway,
        spec_factory,
        ring=ring,
        rollout_probe=(
            registry_rollout_probe(registry_dir) if registry_dir else None
        ),
        metrics=metrics,
        incidents=obs.get("incidents"),
    )


def build_lifecycle(args, metrics: MetricsRegistry, obs: dict, serve_url: str):
    """Assemble the lifecycle controller from the deploy flags
    (docs/lifecycle.md): the fleet's own telemetry ring is the drift
    sensor AND the transition log, its incident recorder snapshots
    aborts/rollbacks, its metrics registry exports ``pio_lifecycle_*``
    through the gateway's federated /metrics, and the gateway itself is
    the cache-warm target (warm queries take the same least-loaded route
    production traffic does)."""
    from predictionio_tpu.lifecycle import (
        LifecycleConfig,
        LifecycleController,
        LifecyclePolicy,
        build_grid_tuner,
        build_warmer,
    )
    from predictionio_tpu.registry.probe import registry_rollout_probe
    from predictionio_tpu.workflow.engine_loader import load_manifest

    registry_dir = getattr(args, "registry_dir", None) or os.environ.get(
        "PIO_REGISTRY_DIR"
    )
    if not registry_dir:
        raise ValueError(
            "--lifecycle stages and promotes through the registry; it "
            "needs --registry-dir (or $PIO_REGISTRY_DIR)"
        )
    manifest = load_manifest(
        getattr(args, "engine_dir", "."), getattr(args, "variant", None)
    )

    def flag(name, default, cast):
        value = getattr(args, name, None)
        return default if value is None else cast(value)

    config = LifecycleConfig(
        cadence_s=flag("lifecycle_cadence", 0.0, float),
        cooldown_s=flag("lifecycle_cooldown", 600.0, float),
        warm_limit=flag("lifecycle_warm_limit", 256, int),
    )
    state_dir = os.path.join(obs["dir"], "lifecycle")
    cwd = os.getcwd()
    if cwd not in sys.path:
        sys.path.insert(0, cwd)
    tuner = build_grid_tuner(
        args.lifecycle,
        workdir=os.path.join(state_dir, "grid"),
        engine_manifest=manifest,
        registry_dir=registry_dir,
        workers=flag("lifecycle_workers", 2, int),
        nice=flag("lifecycle_nice", 10, int),
        cwd=cwd,
        env={k: v for k, v in os.environ.items() if k.startswith("PIO_")},
    )
    warmer = None
    app_name = getattr(args, "lifecycle_app", None)
    if app_name and config.warm_limit > 0:
        from predictionio_tpu.lifecycle.warm import event_store_queries

        def query_source():
            # storage resolves lazily at warm time: the event store may
            # not even exist when the fleet boots
            from predictionio_tpu.data.storage import Storage
            from predictionio_tpu.data.store.event_store import resolve_app

            storage = Storage.instance()
            app_id, _ = resolve_app(storage, app_name, None)
            return event_store_queries(
                storage, app_id, limit=config.warm_limit
            )

        warmer = build_warmer(serve_url, query_source, limit=config.warm_limit)
    return LifecycleController(
        LifecyclePolicy(config),
        state_dir=state_dir,
        engine_id=manifest.engine_id,
        registry_dir=registry_dir,
        tune=tuner,
        warm=warmer,
        rollout_probe=registry_rollout_probe(registry_dir),
        # the SHARED ring object: drift records written by replicas/obs
        # plane land where the controller reads, and its transitions land
        # where `pio top --history` renders
        ring=obs.get("telemetry"),
        incidents=obs.get("incidents"),
        metrics=metrics,
    )


def build_obs_plane(
    obs_dir: str | None,
    metrics: MetricsRegistry,
    registry_dir: str | None = None,
) -> dict:
    """The fleet flight-recorder wiring: worker logbook, telemetry ring,
    incident recorder (all under ``obs_dir``; empty/None disables).
    Returns the pieces keyed by role plus the supervisor ``on_crash``
    hook. Split out of :func:`run_fleet` so tests and the chaos e2e can
    assemble the identical plane around in-process fleets."""
    if not obs_dir:
        return {}
    obs_dir = os.path.abspath(obs_dir)
    logbook = WorkerLogBook(os.path.join(obs_dir, "logs"))
    telemetry = TelemetryRing(os.path.join(obs_dir, "telemetry"))
    incidents = IncidentRecorder(
        os.path.join(obs_dir, "incidents"), metrics=metrics
    )
    if registry_dir:

        def registry_state() -> dict:
            # lazy import: the launcher must not pay the registry import
            # unless an incident actually captures
            from predictionio_tpu.registry.store import ArtifactStore

            store = ArtifactStore(registry_dir)
            out: dict = {}
            for engine_key in store.engines():
                state = store.state_by_key(engine_key)
                out[engine_key] = {
                    "generation": state.generation,
                    "stable": state.stable,
                    "candidate": state.candidate,
                    "mode": state.mode,
                    "fraction": state.fraction,
                }
            return out

        incidents.add_source("registry", registry_state)
    incidents.add_source(
        "telemetry", lambda: telemetry.tail(120)
    )

    def on_crash(info: dict) -> None:
        texts = {}
        tail = info.pop("stderrTail", None)
        if tail:
            texts["stderr_tail"] = tail
        incidents.trigger(
            "worker-park" if info.get("parked") else "worker-crash",
            context=info,
            texts=texts,
        )

    return {
        "dir": obs_dir,
        "logbook": logbook,
        "telemetry": telemetry,
        "incidents": incidents,
        "on_crash": on_crash,
    }


def wire_incident_sources(
    incidents, gateway: Gateway, supervisor: Supervisor
) -> None:
    """Attach the live-state evidence sources once both tiers exist: the
    gateway's merged trace snapshot (its own ring + the per-tick replica
    caches — a SIGKILLed worker's final spans survive in the cache) and
    the supervisor's restart ladder."""
    if incidents is None:
        return
    incidents.add_source("traces", lambda: gateway.cached_spans()[:400])
    incidents.add_source("fleet", gateway.fleet_snapshot)
    incidents.add_source("supervisor", supervisor.snapshot)
    # profile-on-alert (obs/sampler): every incident kind — not just the
    # gateway's own slo-alert path — carries the gateway host-stack view
    incidents.add_source("hoststacks", gateway.sampler.snapshot)


__all__ = [
    "build_autoscaler",
    "build_lifecycle",
    "build_obs_plane",
    "run_fleet",
    "wire_incident_sources",
    "worker_argv",
]
