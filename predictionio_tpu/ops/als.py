"""Alternating least squares on TPU.

Replaces MLlib ALS (used by every reference recommendation template, e.g.
``tests/pio_tests/engines/recommendation-engine/src/main/scala/ALSAlgorithm.scala:79-85``)
with an ALX-style formulation (PAPERS.md: "ALX: Large Scale Matrix
Factorization on TPUs"): instead of Spark's shuffle-join of factor blocks,
each half-iteration builds per-entity normal equations with static-shape
chunked scatter-adds over the COO rating list, then solves all f-by-f systems
batched (MXU-friendly einsums + batched Cholesky).

Design notes (TPU):
  - COO triples are padded to a chunk multiple; padded rows scatter into a
    dummy entity row so shapes stay static under jit.
  - The nnz loop is a ``lax.scan`` over fixed-size chunks: each chunk gathers
    opposite-side factors, forms rank-1 Gram contributions via one einsum
    (``cf,cg->cfg``), and scatter-adds into the per-entity ``A``/``b``
    accumulators. No data-dependent shapes anywhere.
  - Explicit mode solves ``(A_u + reg*n_u*I) x = b_u`` per entity, where
    ``n_u`` is the entity's rating count — the ALS-WR degree-scaled
    regularization (Zhou et al., "Large-scale Parallel Collaborative
    Filtering for the Netflix Prize"; the same weighted-λ scheme MLlib's
    ALS popularized). This is a *numerical requirement* on TPU, not a
    style choice: under a power-law item popularity (bench triage round 3:
    the zipf head item carries ~25% of all ratings at ML-20M scale) the
    hub entity's Gram matrix ``Σ u u^T`` accumulates millions of fp32
    rank-1 terms, its condition number blows up, Cholesky hits a
    rounding-induced negative pivot, and the NaNs take the whole model
    down within two further iterations. Degree-scaled reg keeps the
    regularizer proportional to the Gram magnitude, so conditioning is
    degree-invariant. ``ALSConfig.reg_scaling`` selects: ``auto`` (degree
    for explicit, constant for implicit — implicit's shared ``V^T V``
    dense term already regularizes hubs), ``degree``, or ``constant``.
    Implicit mode (ref ``ALS.trainImplicit``) uses the classic trick:
    ``A_u = V^T V + Σ_i (c_i - 1) v_i v_i^T + reg*I`` with confidence
    ``c = 1 + alpha * r``, so the dense term is a single f×f matmul shared
    across entities.
  - Under a mesh, entity accumulators are sharded over the ``data`` axis and
    the COO chunks are sharded the same way; GSPMD inserts the all-gathers /
    reduce-scatters for cross-shard scatters. Callers annotate via
    ``in_shardings`` on the jitted step (see models/recommendation).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 16
    iterations: int = 10
    reg: float = 0.1  # lambda
    implicit: bool = False
    alpha: float = 1.0  # implicit confidence scale
    seed: int = 3
    chunk: int = 16384  # COO entries per scan step (blocked: block_d * blocks)
    block_d: int = 128  # entity-block width for the MXU Gram path
    # "cg" | "cg_fused" | "cholesky": batched f-by-f SPD solver.
    # Jacobi-preconditioned CG run for f+4 iterations is exact-termination
    # on an f-dim Krylov space (it IS a direct method for these sizes,
    # modulo fp rounding) and maps to batched MXU matvecs — measured 9x
    # faster than jnp.linalg.cholesky + cho_solve for 138k 32x32 systems on
    # a v5e chip, with a smaller residual. "cg_fused" is the identical
    # algorithm as a VMEM-resident pallas kernel: one HBM read of the
    # [n, f, f] systems instead of f+4 (the dominant term of the HBM
    # roofline model, docs/PERF.md); falls back to plain cg off-TPU.
    solver: str = "cg"
    # "auto" | "degree" | "constant" — see module docstring (ALS-WR)
    reg_scaling: str = "auto"
    # "f32" | "bf16": dtype of the FIXED factor table the nnz loop gathers
    # from. The solver iterations are gather-bound (PERF.md: ~21M row
    # gathers/iter dwarf the MXU Gram einsum), so halving the row bytes is
    # the remaining single-chip lever. "bf16" keeps a bf16 COPY of the
    # opposite side for the gather only — Gram/b accumulation, the shared
    # implicit gram term, regularization, and the batched solves all stay
    # f32, so only the gathered operand is rounded (8-bit mantissa).
    gather_dtype: str = "f32"
    # "auto" | "device" | "host": how the COO list becomes MXU block tables.
    # "device" (= "auto"): host does ONE O(n) stable group-by-user (native
    # C++ counting sort, numpy fallback), uploads the minimal wire form
    # (opposite-entity column + ratings + two tiny degree histograms; the
    # grouped-by order makes the user column itself redundant), and the
    # device rebuilds everything else — user column via scatter+cumsum
    # over the degree prefix (see _device_pack; the searchsorted
    # formulation measured 90x slower), the item-side ordering via one
    # stable device sort (~0.13s for 20M triples on v5e), and both block
    # tables via gather-expansion (no scatters). Round-4 decomposition on the real
    # chip showed the old all-host pack at 12.1s and its 350MB padded
    # upload at 10.3s over the ~33MB/s tunnel; this path cuts both.
    # "host" keeps the original numpy block packing (exact reference for
    # tests; also the fallback for empty inputs).
    pack: str = "auto"

    def __post_init__(self):
        # a typo'd reg_scaling silently reverting to constant reg would
        # reintroduce the hub-entity NaN blowup the docstring describes
        if self.reg_scaling not in ("auto", "degree", "constant"):
            raise ValueError(
                f"reg_scaling must be auto|degree|constant, got {self.reg_scaling!r}"
            )
        if self.solver not in ("cg", "cg_fused", "cholesky"):
            raise ValueError(
                f"solver must be cg|cg_fused|cholesky, got {self.solver!r}"
            )
        if self.pack not in ("auto", "device", "host"):
            raise ValueError(f"pack must be auto|device|host, got {self.pack!r}")
        if self.gather_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"gather_dtype must be f32|bf16, got {self.gather_dtype!r}"
            )

    @property
    def degree_scaled_reg(self) -> bool:
        if self.reg_scaling == "auto":
            return not self.implicit
        return self.reg_scaling == "degree"


def _pad_coo(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, chunk: int, dummy_row: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = rows.shape[0]
    pad = (-n) % chunk
    if pad:
        rows = np.concatenate([rows, np.full(pad, dummy_row, rows.dtype)])
        cols = np.concatenate([cols, np.zeros(pad, cols.dtype)])
        vals = np.concatenate([vals, np.zeros(pad, vals.dtype)])
    return rows, cols, vals


def _block_coo(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    d: int,
    block_chunk: int,
    dummy_row: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pack a COO rating list into fixed-width entity blocks (ALX layout).

    Sorts by row, then gives each entity ``ceil(degree / d)`` consecutive
    blocks of ``d`` slots; unused slots carry weight 0. High-degree hub
    entities simply span many blocks — the degree skew that breaks padded
    dense layouts (one row per entity) costs only ``ceil`` waste here.
    Returns ``(block_rows [NB], cols [NB, d], vals [NB, d], w [NB, d])``
    with NB padded to a ``block_chunk`` multiple using dummy-row blocks;
    ``block_rows`` is sorted ascending (dummy = max index last), which the
    device-side scatter declares via ``indices_are_sorted``.
    """
    n = rows.shape[0]
    if n == 0:
        nb = block_chunk
        return (
            np.full((nb,), dummy_row, np.int32),
            np.zeros((nb, d), np.int32),
            np.zeros((nb, d), np.float32),
            np.zeros((nb, d), np.int8),  # same wire dtype as non-empty path
        )
    order = np.argsort(rows, kind="stable")
    r, c, v = rows[order], cols[order], vals[order]
    uniq, start, deg = np.unique(r, return_index=True, return_counts=True)
    nblk = -(-deg // d)
    block_base = np.concatenate([[0], np.cumsum(nblk)])
    nb_real = int(block_base[-1])
    nb = max(nb_real + (-nb_real) % block_chunk, block_chunk)
    # position of each entry within its entity -> (block, slot)
    p = np.arange(n) - np.repeat(start, deg)
    eidx = np.repeat(np.arange(len(uniq)), deg)
    dest_block = block_base[eidx] + p // d
    dest_slot = p % d
    cols_pad = np.zeros((nb, d), np.int32)
    vals_pad = np.zeros((nb, d), np.float32)
    # int8 mask: a quarter of the f32 host->device bytes (the block tables
    # cross the wire once per train; on a remote-attached chip the upload
    # is a measurable slice of total train wall); cast to f32 on device
    w_pad = np.zeros((nb, d), np.int8)
    cols_pad[dest_block, dest_slot] = c
    vals_pad[dest_block, dest_slot] = v
    w_pad[dest_block, dest_slot] = 1
    block_rows = np.full((nb,), dummy_row, np.int32)
    block_rows[:nb_real] = np.repeat(uniq, nblk)
    return block_rows, cols_pad, vals_pad, w_pad


def _normal_equations(
    rows: jnp.ndarray,  # [nnz] entity index being solved (incl. dummy)
    cols: jnp.ndarray,  # [nnz] opposite entity index
    vals: jnp.ndarray,  # [nnz] rating / confidence input
    opposite: jnp.ndarray,  # [n_opp, f] fixed factors
    n_entities: int,  # includes dummy row
    chunk: int,
    implicit: bool,
    alpha: float,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Accumulate A [E, f, f], b [E, f], and rating counts [E] over
    fixed-size COO chunks. Counts feed degree-scaled regularization; the
    dummy padding row accumulates garbage counts, which is harmless (its
    solution is discarded)."""
    f = opposite.shape[1]
    n_chunks = rows.shape[0] // chunk
    A0 = jnp.zeros((n_entities, f, f), opposite.dtype)
    b0 = jnp.zeros((n_entities, f), opposite.dtype)
    n0 = jnp.zeros((n_entities,), opposite.dtype)

    r_ch = rows.reshape(n_chunks, chunk)
    c_ch = cols.reshape(n_chunks, chunk)
    v_ch = vals.reshape(n_chunks, chunk)

    def step(carry, inputs):
        A, b, n = carry
        r, c, v = inputs
        vecs = opposite[c]  # [chunk, f] gather
        if implicit:
            # confidence c_i = 1 + alpha * r; contribution (c_i - 1) v v^T,
            # preference p = 1 -> b contribution c_i * v
            conf_minus_1 = alpha * v
            outer_w = conf_minus_1
            b_w = 1.0 + alpha * v
        else:
            outer_w = jnp.ones_like(v)
            b_w = v
        outers = jnp.einsum("c,cf,cg->cfg", outer_w, vecs, vecs)
        A = A.at[r].add(outers)
        b = b.at[r].add(b_w[:, None] * vecs)
        n = n.at[r].add(jnp.ones_like(v))
        return (A, b, n), None

    (A, b, n), _ = lax.scan(step, (A0, b0, n0), (r_ch, c_ch, v_ch))
    return A, b, n


def _normal_equations_blocked(
    block_rows: jnp.ndarray,  # [NB] owning entity per block (sorted, incl. dummy)
    cols: jnp.ndarray,  # [NB, D] opposite-entity indices
    vals: jnp.ndarray,  # [NB, D] ratings (0 in pad slots)
    w: jnp.ndarray,  # [NB, D] 1.0 real / 0.0 pad
    opposite: jnp.ndarray,  # [n_opp, f] fixed factors
    n_entities: int,
    block_chunk: int,
    implicit: bool,
    alpha: float,
    gather_dtype: str = "f32",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Block-Gram accumulation: the MXU path for the nnz loop.

    The chunked-scatter formulation (``_normal_equations``) spends one
    rank-1 [f,f] outer product + one scatter-add PER RATING — measured
    ~7.4s/iteration at ML-20M on a v5e chip, entirely scatter-bound (the
    ``indices_are_sorted`` hint bought nothing). Here each fixed-width
    entity block computes its Gram contribution as ONE batched matmul
    (``bdf,bdg->bfg`` — contraction depth D rides the MXU) and only the
    per-BLOCK [f,f] results are scattered: D times fewer scatter elements
    and the FLOPs move from the VPU to the MXU.

    ``gather_dtype="bf16"`` gathers from a bf16 copy of ``opposite``
    (half the row bytes on the gather-bound path); accumulation and the
    returned A/b/counts are always at least f32 (callers may pass an
    ``opposite`` that is ALREADY bf16 — e.g. the sharded path's bf16
    all_gather — without the accumulators degrading to bf16).
    """
    f = opposite.shape[1]
    acc_dtype = jnp.promote_types(opposite.dtype, jnp.float32)
    gathered = (
        opposite.astype(jnp.bfloat16) if gather_dtype == "bf16" else opposite
    )
    nb = block_rows.shape[0]
    n_chunks = nb // block_chunk
    A0 = jnp.zeros((n_entities, f, f), acc_dtype)
    b0 = jnp.zeros((n_entities, f), acc_dtype)
    n0 = jnp.zeros((n_entities,), acc_dtype)

    br_ch = block_rows.reshape(n_chunks, block_chunk)
    c_ch = cols.reshape(n_chunks, block_chunk, -1)
    v_ch = vals.reshape(n_chunks, block_chunk, -1)
    w_ch = w.reshape(n_chunks, block_chunk, -1)

    def step(carry, inputs):
        A, b, n = carry
        br, c, v, ww = inputs
        ww = ww.astype(acc_dtype)  # int8 wire format -> f32 math
        vecs = gathered[c]  # [CB, D, f] gather (bf16 rows when opted in)
        if implicit:
            ow = ww * (alpha * v)  # (conf - 1), 0 in pad slots
            bw = ww * (1.0 + alpha * v)
        else:
            ow = ww
            bw = ww * v
        # weights stay f32 on every mode (the f32*bf16 product promotes, so
        # ONLY the gathered rows are rounded — the documented contract; the
        # multiply precision was never the bottleneck, the gather bytes are)
        # and the einsums accumulate in acc_dtype
        A_blk = jnp.einsum(
            "bdf,bdg->bfg",
            ow[..., None] * vecs,
            vecs,
            preferred_element_type=acc_dtype,
        ).astype(acc_dtype)
        b_blk = jnp.einsum(
            "bd,bdf->bf", bw, vecs, preferred_element_type=acc_dtype
        ).astype(acc_dtype)
        n_blk = ww.sum(axis=-1)
        A = A.at[br].add(A_blk, indices_are_sorted=True)
        b = b.at[br].add(b_blk, indices_are_sorted=True)
        n = n.at[br].add(n_blk, indices_are_sorted=True)
        return (A, b, n), None

    (A, b, n), _ = lax.scan(step, (A0, b0, n0), (br_ch, c_ch, v_ch, w_ch))
    return A, b, n


def _batched_spd_solve(A: jnp.ndarray, b: jnp.ndarray, solver: str) -> jnp.ndarray:
    """Solve B independent f-by-f SPD systems. ``cg`` = Jacobi-preconditioned
    conjugate gradient for f+4 iterations (exact termination on the f-dim
    space; batched matvecs ride the MXU — see ALSConfig.solver); ``cg_fused``
    = the same algorithm as a VMEM-resident pallas kernel (one HBM read of
    A instead of f+4 — ops/spd_solve.py); ``cholesky`` = LAPACK-style
    factorization (reference semantics, slower on TPU)."""
    if solver == "cg_fused":
        from predictionio_tpu.ops.spd_solve import batched_spd_solve_auto

        return batched_spd_solve_auto(A, b)
    if solver == "cholesky":
        return jax.scipy.linalg.cho_solve((jnp.linalg.cholesky(A), True), b)
    # stock cg = the SAME body the fused kernel runs (ops/spd_solve.py);
    # one shared implementation keeps the fused/stock parity contract
    # from silently drifting
    from predictionio_tpu.ops.spd_solve import _cg_body

    return _cg_body(A, b, A.shape[-1] + 4, unroll=False)


def _solve_blocked(
    block_rows,
    cols,
    vals,
    w,
    opposite,
    n_entities,
    block_chunk,
    reg,
    implicit,
    alpha,
    degree_scaled_reg: bool,
    solver: str = "cg",
    gather_dtype: str = "f32",
):
    f = opposite.shape[1]
    A, b, counts = _normal_equations_blocked(
        block_rows, cols, vals, w, opposite, n_entities, block_chunk, implicit, alpha,
        gather_dtype,
    )
    eye = jnp.eye(f, dtype=A.dtype)
    if implicit:
        # shared dense term accumulates at the (>= f32) accumulator dtype
        # even if ``opposite`` arrived bf16 from a caller
        gram = jnp.einsum(
            "df,dg->fg", opposite, opposite, preferred_element_type=A.dtype
        )
        A = A + gram[None, :, :]
    if degree_scaled_reg:
        A = A + (reg * jnp.maximum(counts, 1.0))[:, None, None] * eye[None, :, :]
    else:
        A = A + reg * eye[None, :, :]
    return _batched_spd_solve(A, b, solver)


def _solve_side(
    rows,
    cols,
    vals,
    opposite,
    n_entities,
    chunk,
    reg,
    implicit,
    alpha,
    degree_scaled_reg: bool = True,
    solver: str = "cg",
):
    f = opposite.shape[1]
    A, b, counts = _normal_equations(
        rows, cols, vals, opposite, n_entities, chunk, implicit, alpha
    )
    eye = jnp.eye(f, dtype=opposite.dtype)
    if implicit:
        gram = opposite.T @ opposite  # shared dense term, one f x f matmul
        A = A + gram[None, :, :]
    if degree_scaled_reg:
        # ALS-WR: λ·n_e·I — degree-invariant conditioning (module docstring)
        scale = jnp.maximum(counts, 1.0)
        A = A + (reg * scale)[:, None, None] * eye[None, :, :]
    else:
        A = A + reg * eye[None, :, :]
    return _batched_spd_solve(A, b, solver)


# One ALS iteration per executable launch — deliberately NOT a fused
# fori_loop over iterations. Round-3 triage of the round-2 bench crash
# found two hard reasons:
#   1. The remote-attach TPU runtime kills any single program execution
#      running longer than ~60s (surfaces as an opaque UNAVAILABLE device
#      fault at the next fetch). At ML-20M scale one iteration is seconds
#      of device time, so a 10-iteration fused loop is guaranteed dead.
#   2. A fused loop with a static trip count gets unrolled by XLA (compile
#      time scales with iterations) and with a traced trip count hides
#      per-iteration progress.
# Host-looped dispatch costs one dispatch RTT per iteration (negligible
# against seconds of device work), keeps every launch far under the
# watchdog, never recompiles when `iterations` changes, and gives the
# trainer natural mid-train checkpoint/convergence hooks. Factors and the
# COO tables stay resident on device across launches.
@functools.partial(
    jax.jit,
    static_argnames=(
        "n_users",
        "n_items",
        "reg",
        "implicit",
        "alpha",
        "block_chunk",
        "degree_scaled_reg",
        "solver",
        "gather_dtype",
    ),
    donate_argnums=(0, 1),
)
def _als_step(
    user_factors,
    item_factors,
    u_br,
    u_cols,
    u_vals,
    u_w,
    i_br,
    i_cols,
    i_vals,
    i_w,
    *,
    n_users: int,
    n_items: int,
    reg: float,
    implicit: bool,
    alpha: float,
    block_chunk: int,
    degree_scaled_reg: bool = True,
    solver: str = "cg",
    gather_dtype: str = "f32",
):
    user_factors = _solve_blocked(
        u_br, u_cols, u_vals, u_w, item_factors, n_users + 1, block_chunk,
        reg, implicit, alpha, degree_scaled_reg, solver, gather_dtype,
    )
    item_factors = _solve_blocked(
        i_br, i_cols, i_vals, i_w, user_factors, n_items + 1, block_chunk,
        reg, implicit, alpha, degree_scaled_reg, solver, gather_dtype,
    )
    return user_factors, item_factors


@functools.partial(jax.jit, static_argnames=("n_users", "n_items", "rank", "seed"))
def _als_init(*, n_users: int, n_items: int, rank: int, seed: int):
    key = jax.random.PRNGKey(seed)
    # +1 dummy row absorbs padding scatters
    item_factors = (
        jax.random.normal(key, (n_items + 1, rank), jnp.float32) / jnp.sqrt(rank)
    )
    user_factors = jnp.zeros((n_users + 1, rank), jnp.float32)
    return user_factors, item_factors


def _expand_blocks_traced(deg, cols_sorted, vals_sorted, d: int, nb: int, dummy_row: int):
    """Device-side equivalent of ``_block_coo`` for an already-grouped side.

    Inputs are grouped by owning entity (ascending, stable); ``deg`` is the
    per-entity count. Builds the [nb, d] block tables with searchsorted +
    gathers only — no scatters (TPU scatters of 20M elements are the thing
    the blocked layout exists to avoid). Produces the exact layout
    ``_block_coo`` computes: entity e owns ``ceil(deg[e]/d)`` consecutive
    blocks; pad slots carry weight 0; pad blocks point at ``dummy_row``.
    """
    n_entities = deg.shape[0]
    nblk = (deg + (d - 1)) // d
    bb_incl = jnp.cumsum(nblk)  # inclusive block prefix
    block_base = bb_incl - nblk
    start = jnp.cumsum(deg) - deg
    b = jnp.arange(nb, dtype=jnp.int32)
    # owner[b] = first entity whose inclusive block prefix exceeds b;
    # == n_entities for pad blocks past the real range
    owner = jnp.searchsorted(bb_incl, b, side="right").astype(jnp.int32)
    is_real = owner < n_entities
    e = jnp.minimum(owner, n_entities - 1)
    local = b - block_base[e]
    offs = local[:, None] * d + jnp.arange(d, dtype=jnp.int32)[None, :]
    valid = is_real[:, None] & (offs < deg[e][:, None])
    src = jnp.where(valid, start[e][:, None] + offs, 0)
    cols_b = jnp.where(valid, cols_sorted[src], 0).astype(jnp.int32)
    vals_b = jnp.where(valid, vals_sorted[src], jnp.float32(0))
    w_b = valid.astype(jnp.int8)
    block_rows = jnp.where(is_real, e, jnp.int32(dummy_row))
    return block_rows, cols_b, vals_b, w_b


@functools.partial(
    jax.jit, static_argnames=("d", "nb_u", "nb_i", "n_users", "n_items")
)
def _device_pack(
    cols_u,  # [nnz] opposite (item) ids grouped by user; int16 or int32 wire
    vals_u,  # [nnz] ratings grouped by user; uint8 codes / float16 / float32
    deg_u,  # [n_users] int32 per-user rating count
    deg_i,  # [n_items] int32 per-item rating count
    val_table=None,  # [<=256] f32 dictionary for uint8-coded ratings
    *,
    d: int,
    nb_u: int,
    nb_i: int,
    n_users: int,
    n_items: int,
):
    """Build BOTH sides' block tables on device from the minimal wire form.

    The user column is implicit in the grouped order (reconstructed via
    searchsorted over the degree prefix sum); the item-side ordering comes
    from one stable device sort. Saves ~2/3 of the H2D bytes vs uploading
    two padded block-table sets, and all the host pack time past the one
    counting sort.
    """
    nnz = cols_u.shape[0]
    items_u = cols_u.astype(jnp.int32)
    if val_table is not None:
        # dictionary-coded wire: one tiny-table gather decodes exactly
        ratings_u = val_table[vals_u.astype(jnp.int32)]
    else:
        ratings_u = vals_u.astype(jnp.float32)
    # user column from the grouped order: +1 at each entity's start position,
    # then an inclusive cumsum. O(n) in two passes — the searchsorted
    # formulation (binary search = ~17 gather passes over the prefix array)
    # measured 2.7s for 19.6M rows on a v5e; this is 0.03s
    start_u = jnp.cumsum(deg_u) - deg_u
    users_u = jnp.cumsum(
        jnp.zeros((nnz,), jnp.int32).at[start_u[1:]].add(1)
    )
    u_tables = _expand_blocks_traced(deg_u, items_u, ratings_u, d, nb_u, n_users)
    _, users_by_item, ratings_by_item = lax.sort(
        (items_u, users_u, ratings_u), num_keys=1, is_stable=True
    )
    i_tables = _expand_blocks_traced(
        deg_i, users_by_item, ratings_by_item, d, nb_i, n_items
    )
    return (*u_tables, *i_tables)


def _compress_ratings_wire(
    vals: "np.ndarray",
) -> tuple["np.ndarray", "np.ndarray | None"]:
    """Smallest LOSSLESS wire form of the ratings column; returns
    ``(wire_vals, table)``.

    - ≤256 distinct values (every real star-rating dataset: ML uses 0.5
      steps over [0.5, 5]) -> uint8 dictionary codes + a tiny f32 value
      table, decoded on device by one gather — 4x smaller than f32;
    - else f16 when every value round-trips exactly;
    - else untouched f32 — no quality-for-bandwidth trade is ever silent.

    Distinctness is probed on a 65536-sample first (one tiny unique)
    so the continuous case never pays a full-array sort; the candidate
    table is then verified exactly against the full column.
    """
    if vals.shape[0] == 0:
        return vals, None
    sample_uniq = np.unique(vals[:65536])
    if 0 < sample_uniq.size <= 256:
        idx = np.searchsorted(sample_uniq, vals)
        idx = np.minimum(idx, sample_uniq.size - 1)
        if np.array_equal(sample_uniq[idx], vals):
            return idx.astype(np.uint8), sample_uniq.astype(np.float32)
    v16 = vals.astype(np.float16)
    if np.array_equal(v16.astype(np.float32), vals):
        return v16, None
    return vals, None


def _host_group_by(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, n_entities: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stable group-by-entity: native C++ counting sort (O(n), one pass each
    for histogram and scatter) with a numpy stable-argsort fallback.

    Ids must lie in [0, n_entities): an oversized id would give the degree
    histogram the wrong length and every downstream block table a silently
    corrupt layout (JAX clips the OOB gathers instead of failing), so it is
    rejected here on both paths."""
    if rows.shape[0] and int(rows.max()) >= n_entities:
        raise ValueError(
            f"entity index {int(rows.max())} out of range for {n_entities} entities"
        )
    from predictionio_tpu.utils import native

    out = native.coo_group(rows, cols, vals, n_entities)
    if out is not None:
        return out
    order = np.argsort(rows, kind="stable")
    deg = np.bincount(rows, minlength=n_entities).astype(np.int32)
    return cols[order], vals[order], deg


def _pad_blocks(nb_real: int, block_chunk: int) -> int:
    return max(nb_real + (-nb_real) % block_chunk, block_chunk)


@jax.jit
def _barrier_checksum(*arrays):
    """One scalar derived from every input array (barrier helper)."""
    total = jnp.float32(0)
    for a in arrays:
        total = total + jnp.sum(a, dtype=jnp.float32)
    return total


def fetch_barrier(*arrays) -> float:
    """TRUE completion barrier that works on remote-attached devices.

    ``block_until_ready`` only acks *dispatch* through a network tunnel, and
    fetching a slice of a buffer can be served before dependent computation
    finishes (round-3 bench triage: a 10-iteration ALS run "blocked" in 3.5s
    and then stalled 158s inside the next readback, so the old slope probe
    measured dispatch twice and published an MFU of 89 million percent).
    Fetching a freshly *derived* scalar cannot complete early: the scalar's
    value does not exist until every input array has been materialized.
    Returns the checksum so callers can keep the fetch from being elided.
    """
    # pio-lint: disable=train-unaccounted-sync -- this IS the timing instrument; callers time around it
    return float(np.asarray(_barrier_checksum(*arrays)))


def als_train(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig,
    timings: dict | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Train explicit or implicit ALS; returns (user_factors [n_users, f],
    item_factors [n_items, f]).

    Pass a ``timings`` dict to get a wall-clock decomposition written into
    it: ``pack_s`` (host group-by / block packing), ``upload_s`` (H2D
    transfer of the wire arrays, barrier-confirmed), ``build_s``
    (device-side block-table construction — 0 on the host pack path),
    ``device_s`` (solver iterations only, barrier-confirmed). The
    instrumentation barriers make the decomposition sum to the call's wall
    clock; the un-instrumented path keeps the fully-async dispatch
    pipeline.

    With an active train profile (obs/xray): the host pack/upload/build
    accounts as ``host_etl``, each iteration becomes one profiled
    ``sweep`` step closed by a true device barrier (the barrier per
    iteration serializes the at-most-one-deep dispatch overlap — that is
    the price of per-iteration device time, paid only when profiling),
    the per-iteration factor checksum rides as the step's convergence
    metric, and live-memory peaks are sampled per step.
    """
    import time

    from predictionio_tpu.obs import xray

    prof = xray.current_profile()
    with xray.phase(xray.PHASE_HOST_ETL):
        user_idx = np.asarray(user_idx, np.int32)
        item_idx = np.asarray(item_idx, np.int32)
        ratings = np.asarray(ratings, np.float32)
        valid = (user_idx >= 0) & (item_idx >= 0)
        user_idx, item_idx, ratings = (
            user_idx[valid], item_idx[valid], ratings[valid]
        )
        if user_idx.shape[0]:
            for name, idx, bound in (
                ("user", user_idx, n_users),
                ("item", item_idx, n_items),
            ):
                mx = int(idx.max())
                if mx >= bound:
                    raise ValueError(
                        f"{name} index {mx} out of range for n_{name}s={bound}"
                    )
        d = max(8, min(config.block_d, config.chunk))
        block_chunk = max(8, config.chunk // d)
        use_device_pack = config.pack != "host" and user_idx.shape[0] > 0

        t0 = time.perf_counter()
        if use_device_pack:
            cols_u, vals_u, deg_u = _host_group_by(
                user_idx, item_idx, ratings, n_users
            )
            deg_i = np.bincount(item_idx, minlength=n_items).astype(np.int32)
            nb_u = _pad_blocks(int((-(-deg_u // d)).sum()), block_chunk)
            nb_i = _pad_blocks(int((-(-deg_i // d)).sum()), block_chunk)
            # wire compression, all LOSSLESS: opposite ids as int16 when the
            # vocab fits; ratings in their smallest exact form (uint8
            # dictionary codes / f16 / f32 — see _compress_ratings_wire).
            # H2D rides a ~33MB/s tunnel here — bytes are wall-clock.
            if n_items <= np.iinfo(np.int16).max:
                cols_u = cols_u.astype(np.int16)
            vals_u, val_table = _compress_ratings_wire(vals_u)
            t_pack = time.perf_counter()
            wire = [jax.device_put(a) for a in (cols_u, vals_u, deg_u, deg_i)]
            table_dev = (
                jax.device_put(val_table) if val_table is not None else None
            )
            if timings is not None:
                fetch_barrier(*wire)
            t_upload = time.perf_counter()
            dev = list(
                _device_pack(
                    *wire, val_table=table_dev,
                    d=d, nb_u=nb_u, nb_i=nb_i, n_users=n_users, n_items=n_items,
                )
            )
            if timings is not None:
                # device-side table build (sort + gather expansion) attributed
                # to its own bucket: device_s means SOLVER iterations only, on
                # both pack paths, or per-iteration figures aren't comparable
                fetch_barrier(dev[0], dev[4])
            t_build = time.perf_counter()
        else:
            u_blocks = _block_coo(
                user_idx, item_idx, ratings, d, block_chunk, n_users
            )
            i_blocks = _block_coo(
                item_idx, user_idx, ratings, d, block_chunk, n_items
            )
            t_pack = time.perf_counter()
            # block tables cross host->device ONCE; the per-iteration
            # launches reuse the same device buffers
            dev = [jax.device_put(a) for a in (*u_blocks, *i_blocks)]
            if timings is not None:
                fetch_barrier(*dev)
            t_upload = time.perf_counter()
            t_build = t_upload  # tables arrive pre-built on the host path
        user_f, item_f = _als_init(
            n_users=n_users, n_items=n_items, rank=config.rank, seed=config.seed
        )
    import contextlib

    nnz = int(user_idx.shape[0])
    for _ in range(config.iterations):
        with contextlib.ExitStack() as stack:
            rec = (
                stack.enter_context(prof.step(nnz=nnz))
                if prof is not None
                else None
            )
            with xray.phase(xray.PHASE_SWEEP):
                user_f, item_f = _als_step(
                    user_f,
                    item_f,
                    *dev,
                    n_users=n_users,
                    n_items=n_items,
                    reg=config.reg,
                    implicit=config.implicit,
                    alpha=config.alpha,
                    block_chunk=block_chunk,
                    degree_scaled_reg=config.degree_scaled_reg,
                    solver=config.solver,
                    gather_dtype=config.gather_dtype,
                )
                if rec is not None:
                    rec["metric"] = prof.device_barrier(
                        user_f, item_f, where="als-sweep"
                    )
        if prof is not None:
            # profiler's own bookkeeping (live-array walk) accounts as
            # host_etl so it cannot open a hole in the tiling contract
            with prof.phase(xray.PHASE_HOST_ETL):
                prof.add_rows(nnz)
                prof.sample_memory()
    if timings is not None:
        fetch_barrier(user_f, item_f)
        timings["pack_s"] = t_pack - t0
        timings["upload_s"] = t_upload - t_pack
        timings["build_s"] = t_build - t_upload
        timings["device_s"] = time.perf_counter() - t_build
        # block-table shapes, for the HBM bytes-moved model
        # (solver_hbm_bytes_per_iter): nb = blocks per side, d = block width
        timings["nb_u"] = int(dev[0].shape[0])
        timings["nb_i"] = int(dev[4].shape[0])
        timings["d"] = d
    return user_f[:n_users], item_f[:n_items]


def solver_hbm_bytes_per_iter(
    nb_u: int,
    nb_i: int,
    d: int,
    f: int,
    n_users: int,
    n_items: int,
    *,
    gather_dtype: str = "f32",
    solver: str = "cg",
    implicit: bool = False,
) -> int:
    """Mandatory HBM traffic of one ALS iteration (both half-solves), in
    bytes — the roofline denominator for ``als_hbm_util`` (bytes/iter ÷
    measured iter time ÷ HBM bandwidth). This models the traffic the
    formulation REQUIRES; the measured iteration can only be slower, so
    util > 1 means the timing probe is broken, and util well below ~0.5
    means the implementation (not the memory system) is the bottleneck.

    Per half-solve with NB [d]-wide blocks over n_ent(+1 dummy) entities:

    - block-stream reads: cols int32 + vals f32 + w int8 + the factor-row
      gather (f x 4 bytes, or f x 2 under ``gather_dtype="bf16"``) —
      NB*d*(9 + f*gb);
    - Gram scatter-adds (read+modify+write of the [f,f]+[f]+[1] block
      results): 2*NB*(f^2+f+1)*4;
    - A-matrix assembly/regularization pass: 2*n_ent*f^2*4;
    - cg solve: (f+4) batched matvecs re-reading A from HBM —
      (f+4)*n_ent*f^2*4 — plus ~8 [f]-vector reads/writes per cg step;
      cholesky is modeled as ~2 passes over A;
    - implicit mode adds one shared-gram read of the opposite factors.
    """
    gb = 2 if gather_dtype == "bf16" else 4
    total = 0
    for nb, n_ent, n_opp in (
        (nb_u, n_users + 1, n_items + 1),
        (nb_i, n_items + 1, n_users + 1),
    ):
        stream = nb * d * (9 + f * gb)
        gram_scatter = 2 * nb * (f * f + f + 1) * 4
        assemble = 2 * n_ent * f * f * 4
        if solver == "cg":
            solve = (f + 4) * n_ent * (f * f + 8 * f) * 4
        else:
            solve = 2 * n_ent * f * f * 4
        shared = n_opp * f * 4 if implicit else 0
        total += stream + gram_scatter + assemble + solve + shared
    return int(total)


# ---------------------------------------------------------------------------
# Serving-side scoring
# ---------------------------------------------------------------------------
#
# The hot path (BASELINE's <10ms p50 target) is engineered for minimum
# host<->device round trips, because on a remote-attached TPU every transfer
# is a network RTT and on a local one every transfer is a dispatch:
#   - factor tables stay resident on device (``ServingIndex``),
#   - the query uploads ONE int32 scalar (the user index); the factor gather
#     happens on device,
#   - scores and indices come back in ONE packed int32 fetch. The scores ride
#     as a bitcast (float32 bits are preserved exactly in an int32 lane);
#     packing the *indices* as float32 would be wrong — small indices bitcast
#     to denormal floats, which XLA flushes to zero.


def _pack(scores, idx):
    return jnp.stack([lax.bitcast_convert_type(scores, jnp.int32), idx])


def _unpack(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    return packed[0].view(np.float32), packed[1]


@functools.partial(jax.jit, static_argnames=("k",))
def _serve_by_index(uidx, user_factors, item_factors, mask, k: int):
    scores = item_factors @ user_factors[uidx]  # [n_items]
    scores = jnp.where(mask, scores, -jnp.inf)
    return _pack(*lax.top_k(scores, k))


@functools.partial(jax.jit, static_argnames=("k",))
def _serve_by_index_batch(uidxs, user_factors, item_factors, mask, k: int):
    scores = user_factors[uidxs] @ item_factors.T  # [B, n_items] on the MXU
    scores = jnp.where(mask[None, :], scores, -jnp.inf)
    s, i = lax.top_k(scores, k)
    return jnp.stack([lax.bitcast_convert_type(s, jnp.int32), i], axis=1)


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_scores_packed(user_vec, item_factors, mask, k: int):
    scores = item_factors @ user_vec
    scores = jnp.where(mask, scores, -jnp.inf)
    return _pack(*lax.top_k(scores, k))


def predict_scores(user_vec: jax.Array, item_factors: jax.Array) -> jax.Array:
    return item_factors @ user_vec


def top_k_items(
    user_vec: jax.Array,
    item_factors: jax.Array,
    k: int,
    mask: jax.Array | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """One-shot top-k for an explicit user vector; single packed fetch.
    ``mask`` False = excluded item. Prefer ``ServingIndex`` on the serving
    path — it also keeps the user table resident."""
    if mask is None:
        mask = jnp.ones((item_factors.shape[0],), bool)
    # pio-lint: disable=train-unaccounted-sync -- serving-path fetch, accounted by the request waterfall
    packed = np.asarray(_topk_scores_packed(user_vec, item_factors, mask, k))
    return _unpack(packed)


def next_pow2(n: int) -> int:
    """Bucket-rounding rule shared by the batched predict path and
    ``ServingIndex.warmup_buckets`` — they must agree or warmed shapes won't
    match served shapes and serve-time compiles come back."""
    return 1 << max(0, n - 1).bit_length()


def upload(x, dtype=None):
    """Host->device upload that GUARANTEES the device buffer is decoupled
    from the host array.

    On the CPU backend ``jnp.asarray(host_numpy)`` is ZERO-COPY: the jax
    array aliases the numpy memory. Every async serving dispatch that
    stages its batch in a reused ``ops.topk.ScratchBuffers`` slot then
    races the in-flight kernel against the next batch's assembly — the
    observed failure (offline double-buffer pipeline, CPU backend) was
    batch N's first rows answering with batch N+1's users, a torn read of
    the overwritten staging buffer. ``copy=True`` restores the contract
    the scratch pools are built on: the host buffer is reusable the
    moment the dispatch call returns. Device arrays pass through
    untouched (immutable, nothing to decouple); on non-CPU backends the
    H2D transfer is a copy regardless."""
    if isinstance(x, jax.Array):
        return x
    # pio-lint: disable=train-unaccounted-sync,serving-host-roundtrip -- host staging array (device handles returned above), never a device round-trip
    arr = np.asarray(x) if dtype is None else np.asarray(x, dtype)
    return jnp.asarray(arr, copy=True)


class ServingIndex:
    """Device-resident factor tables with index-addressed top-k serve.

    The TPU replacement for the reference's in-JVM model broadcast
    (``CreateServer.scala:196-200`` deserializes the kryo model into the
    server heap; here the model lives in HBM and every query is one compiled
    kernel). Per-query cost: one int32 upload + one [2,k] int32 fetch
    (row 0 = float32 score bits, row 1 = item indices).
    """

    def __init__(self, user_factors, item_factors):
        self.user_factors = jnp.asarray(user_factors)
        self.item_factors = jnp.asarray(item_factors)
        self._full_mask = jnp.ones((self.item_factors.shape[0],), bool)

    @property
    def n_users(self) -> int:
        return self.user_factors.shape[0]

    @property
    def n_items(self) -> int:
        return self.item_factors.shape[0]

    def warmup(self, k: int) -> None:
        # pio-lint: disable=train-unaccounted-sync -- deploy-time warmup, deliberately synchronous
        jax.block_until_ready(
            _serve_by_index(
                jnp.int32(0), self.user_factors, self.item_factors, self._full_mask, k
            )
        )

    def warmup_buckets(self, k: int, max_batch: int) -> None:
        """Pre-compile every power-of-two batch bucket up to ``max_batch``
        for top-``k`` (k rounded up to its own bucket). The batched predict
        path buckets ragged batch sizes to powers of two; compiling them all
        at deploy time keeps the first ragged burst from paying a compile."""
        kk = min(next_pow2(k), self.n_items)
        b = 1
        handles = []
        # the dispatch path buckets len(batch) <= max_batch up to
        # next_pow2(max_batch), so that is the range to warm (warming only
        # to max_batch would leave e.g. bucket 128 cold for max_batch=100)
        while b <= next_pow2(max_batch):
            handles.append(
                _serve_by_index_batch(
                    jnp.zeros((b,), jnp.int32),
                    self.user_factors,
                    self.item_factors,
                    self._full_mask,
                    kk,
                )
            )
            b *= 2
        # pio-lint: disable=train-unaccounted-sync -- deploy-time warmup, deliberately synchronous
        jax.block_until_ready(handles)

    def serve(
        self, user_index: int, k: int, mask: jax.Array | np.ndarray | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-k (scores, item indices) for one user index."""
        m = self._full_mask if mask is None else jnp.asarray(mask)
        # pio-lint: disable=train-unaccounted-sync -- serving-path fetch, accounted by the request waterfall
        packed = np.asarray(
            _serve_by_index(
                jnp.int32(user_index), self.user_factors, self.item_factors, m, k
            )
        )
        return _unpack(packed)

    def serve_batch(
        self,
        user_indices: np.ndarray,
        k: int,
        mask: jax.Array | np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Micro-batched serve: [B] indices -> ([B,k] scores, [B,k] items).
        This is the throughput path an async query server batches into."""
        return self.unpack_batch(
            # pio-lint: disable=train-unaccounted-sync -- serving-path fetch, accounted by the request waterfall
            np.asarray(self.serve_batch_async(user_indices, k, mask))
        )

    def serve_batch_async(
        self,
        user_indices: np.ndarray | jax.Array,
        k: int,
        mask: jax.Array | np.ndarray | None = None,
    ) -> jax.Array:
        """Non-blocking batched serve: dispatches the kernel and returns the
        packed [B,2,k] int32 device array WITHOUT fetching it. An async query
        server dispatches batch n+1 while fetching batch n's result, so
        device work and transport overlap; decode with ``unpack_batch``."""
        m = self._full_mask if mask is None else upload(mask)
        if isinstance(user_indices, jax.Array):
            # already on device: a np.asarray round-trip would block on a
            # D2H fetch and defeat the non-blocking contract
            idxs = user_indices.astype(jnp.int32)
        else:
            # upload() COPIES: callers stage indices in reusable scratch
            # buffers and overwrite them for the next batch while this
            # batch's kernel is still in flight
            idxs = upload(user_indices, np.int32)
        return _serve_by_index_batch(
            idxs, self.user_factors, self.item_factors, m, k
        )

    @staticmethod
    def unpack_batch(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Decode a fetched [B,2,k] packed result into ([B,k] float32 scores,
        [B,k] int32 item indices)."""
        return (
            np.ascontiguousarray(packed[:, 0, :]).view(np.float32),
            packed[:, 1, :],
        )
