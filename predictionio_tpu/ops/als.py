"""Alternating least squares on TPU.

Replaces MLlib ALS (used by every reference recommendation template, e.g.
``tests/pio_tests/engines/recommendation-engine/src/main/scala/ALSAlgorithm.scala:79-85``)
with an ALX-style formulation (PAPERS.md: "ALX: Large Scale Matrix
Factorization on TPUs"): instead of Spark's shuffle-join of factor blocks,
each half-iteration builds per-entity normal equations with static-shape
chunked scatter-adds over the COO rating list, then solves all f-by-f systems
batched (MXU-friendly einsums + batched Cholesky).

Design notes (TPU):
  - COO triples are padded to a chunk multiple; padded rows scatter into a
    dummy entity row so shapes stay static under jit.
  - The nnz loop is a ``lax.scan`` over fixed-size chunks: each chunk gathers
    opposite-side factors, forms rank-1 Gram contributions via one einsum
    (``cf,cg->cfg``), and scatter-adds into the per-entity ``A``/``b``
    accumulators. No data-dependent shapes anywhere.
  - Explicit mode solves ``(A_u + reg*n_u*I) x = b_u`` per entity, where
    ``n_u`` is the entity's rating count — the ALS-WR degree-scaled
    regularization (Zhou et al., "Large-scale Parallel Collaborative
    Filtering for the Netflix Prize"; the same weighted-λ scheme MLlib's
    ALS popularized). This is a *numerical requirement* on TPU, not a
    style choice: under a power-law item popularity (bench triage round 3:
    the zipf head item carries ~25% of all ratings at ML-20M scale) the
    hub entity's Gram matrix ``Σ u u^T`` accumulates millions of fp32
    rank-1 terms, its condition number blows up, Cholesky hits a
    rounding-induced negative pivot, and the NaNs take the whole model
    down within two further iterations. Degree-scaled reg keeps the
    regularizer proportional to the Gram magnitude, so conditioning is
    degree-invariant. ``ALSConfig.reg_scaling`` selects: ``auto`` (degree
    for explicit, constant for implicit — implicit's shared ``V^T V``
    dense term already regularizes hubs), ``degree``, or ``constant``.
    Implicit mode (ref ``ALS.trainImplicit``) uses the classic trick:
    ``A_u = V^T V + Σ_i (c_i - 1) v_i v_i^T + reg*I`` with confidence
    ``c = 1 + alpha * r``, so the dense term is a single f×f matmul shared
    across entities.
  - Under a mesh, entity accumulators are sharded over the ``data`` axis and
    the COO chunks are sharded the same way; GSPMD inserts the all-gathers /
    reduce-scatters for cross-shard scatters. Callers annotate via
    ``in_shardings`` on the jitted step (see models/recommendation).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 16
    iterations: int = 10
    reg: float = 0.1  # lambda
    implicit: bool = False
    alpha: float = 1.0  # implicit confidence scale
    seed: int = 3
    chunk: int = 16384  # COO entries per scan step (blocked: block_d * blocks)
    block_d: int = 128  # entity-block width for the MXU Gram path
    # "cg" | "cholesky": batched f-by-f SPD solver. Jacobi-preconditioned CG
    # run for f+4 iterations is exact-termination on an f-dim Krylov space
    # (it IS a direct method for these sizes, modulo fp rounding) and maps to
    # batched MXU matvecs — measured 9x faster than jnp.linalg.cholesky +
    # cho_solve for 138k 32x32 systems on a v5e chip, with a smaller residual.
    solver: str = "cg"
    # "auto" | "degree" | "constant" — see module docstring (ALS-WR)
    reg_scaling: str = "auto"

    def __post_init__(self):
        # a typo'd reg_scaling silently reverting to constant reg would
        # reintroduce the hub-entity NaN blowup the docstring describes
        if self.reg_scaling not in ("auto", "degree", "constant"):
            raise ValueError(
                f"reg_scaling must be auto|degree|constant, got {self.reg_scaling!r}"
            )
        if self.solver not in ("cg", "cholesky"):
            raise ValueError(f"solver must be cg|cholesky, got {self.solver!r}")

    @property
    def degree_scaled_reg(self) -> bool:
        if self.reg_scaling == "auto":
            return not self.implicit
        return self.reg_scaling == "degree"


def _pad_coo(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, chunk: int, dummy_row: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = rows.shape[0]
    pad = (-n) % chunk
    if pad:
        rows = np.concatenate([rows, np.full(pad, dummy_row, rows.dtype)])
        cols = np.concatenate([cols, np.zeros(pad, cols.dtype)])
        vals = np.concatenate([vals, np.zeros(pad, vals.dtype)])
    return rows, cols, vals


def _block_coo(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    d: int,
    block_chunk: int,
    dummy_row: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pack a COO rating list into fixed-width entity blocks (ALX layout).

    Sorts by row, then gives each entity ``ceil(degree / d)`` consecutive
    blocks of ``d`` slots; unused slots carry weight 0. High-degree hub
    entities simply span many blocks — the degree skew that breaks padded
    dense layouts (one row per entity) costs only ``ceil`` waste here.
    Returns ``(block_rows [NB], cols [NB, d], vals [NB, d], w [NB, d])``
    with NB padded to a ``block_chunk`` multiple using dummy-row blocks;
    ``block_rows`` is sorted ascending (dummy = max index last), which the
    device-side scatter declares via ``indices_are_sorted``.
    """
    n = rows.shape[0]
    if n == 0:
        nb = block_chunk
        return (
            np.full((nb,), dummy_row, np.int32),
            np.zeros((nb, d), np.int32),
            np.zeros((nb, d), np.float32),
            np.zeros((nb, d), np.int8),  # same wire dtype as non-empty path
        )
    order = np.argsort(rows, kind="stable")
    r, c, v = rows[order], cols[order], vals[order]
    uniq, start, deg = np.unique(r, return_index=True, return_counts=True)
    nblk = -(-deg // d)
    block_base = np.concatenate([[0], np.cumsum(nblk)])
    nb_real = int(block_base[-1])
    nb = max(nb_real + (-nb_real) % block_chunk, block_chunk)
    # position of each entry within its entity -> (block, slot)
    p = np.arange(n) - np.repeat(start, deg)
    eidx = np.repeat(np.arange(len(uniq)), deg)
    dest_block = block_base[eidx] + p // d
    dest_slot = p % d
    cols_pad = np.zeros((nb, d), np.int32)
    vals_pad = np.zeros((nb, d), np.float32)
    # int8 mask: a quarter of the f32 host->device bytes (the block tables
    # cross the wire once per train; on a remote-attached chip the upload
    # is a measurable slice of total train wall); cast to f32 on device
    w_pad = np.zeros((nb, d), np.int8)
    cols_pad[dest_block, dest_slot] = c
    vals_pad[dest_block, dest_slot] = v
    w_pad[dest_block, dest_slot] = 1
    block_rows = np.full((nb,), dummy_row, np.int32)
    block_rows[:nb_real] = np.repeat(uniq, nblk)
    return block_rows, cols_pad, vals_pad, w_pad


def _normal_equations(
    rows: jnp.ndarray,  # [nnz] entity index being solved (incl. dummy)
    cols: jnp.ndarray,  # [nnz] opposite entity index
    vals: jnp.ndarray,  # [nnz] rating / confidence input
    opposite: jnp.ndarray,  # [n_opp, f] fixed factors
    n_entities: int,  # includes dummy row
    chunk: int,
    implicit: bool,
    alpha: float,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Accumulate A [E, f, f], b [E, f], and rating counts [E] over
    fixed-size COO chunks. Counts feed degree-scaled regularization; the
    dummy padding row accumulates garbage counts, which is harmless (its
    solution is discarded)."""
    f = opposite.shape[1]
    n_chunks = rows.shape[0] // chunk
    A0 = jnp.zeros((n_entities, f, f), opposite.dtype)
    b0 = jnp.zeros((n_entities, f), opposite.dtype)
    n0 = jnp.zeros((n_entities,), opposite.dtype)

    r_ch = rows.reshape(n_chunks, chunk)
    c_ch = cols.reshape(n_chunks, chunk)
    v_ch = vals.reshape(n_chunks, chunk)

    def step(carry, inputs):
        A, b, n = carry
        r, c, v = inputs
        vecs = opposite[c]  # [chunk, f] gather
        if implicit:
            # confidence c_i = 1 + alpha * r; contribution (c_i - 1) v v^T,
            # preference p = 1 -> b contribution c_i * v
            conf_minus_1 = alpha * v
            outer_w = conf_minus_1
            b_w = 1.0 + alpha * v
        else:
            outer_w = jnp.ones_like(v)
            b_w = v
        outers = jnp.einsum("c,cf,cg->cfg", outer_w, vecs, vecs)
        A = A.at[r].add(outers)
        b = b.at[r].add(b_w[:, None] * vecs)
        n = n.at[r].add(jnp.ones_like(v))
        return (A, b, n), None

    (A, b, n), _ = lax.scan(step, (A0, b0, n0), (r_ch, c_ch, v_ch))
    return A, b, n


def _normal_equations_blocked(
    block_rows: jnp.ndarray,  # [NB] owning entity per block (sorted, incl. dummy)
    cols: jnp.ndarray,  # [NB, D] opposite-entity indices
    vals: jnp.ndarray,  # [NB, D] ratings (0 in pad slots)
    w: jnp.ndarray,  # [NB, D] 1.0 real / 0.0 pad
    opposite: jnp.ndarray,  # [n_opp, f] fixed factors
    n_entities: int,
    block_chunk: int,
    implicit: bool,
    alpha: float,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Block-Gram accumulation: the MXU path for the nnz loop.

    The chunked-scatter formulation (``_normal_equations``) spends one
    rank-1 [f,f] outer product + one scatter-add PER RATING — measured
    ~7.4s/iteration at ML-20M on a v5e chip, entirely scatter-bound (the
    ``indices_are_sorted`` hint bought nothing). Here each fixed-width
    entity block computes its Gram contribution as ONE batched matmul
    (``bdf,bdg->bfg`` — contraction depth D rides the MXU) and only the
    per-BLOCK [f,f] results are scattered: D times fewer scatter elements
    and the FLOPs move from the VPU to the MXU.
    """
    f = opposite.shape[1]
    nb = block_rows.shape[0]
    n_chunks = nb // block_chunk
    A0 = jnp.zeros((n_entities, f, f), opposite.dtype)
    b0 = jnp.zeros((n_entities, f), opposite.dtype)
    n0 = jnp.zeros((n_entities,), opposite.dtype)

    br_ch = block_rows.reshape(n_chunks, block_chunk)
    c_ch = cols.reshape(n_chunks, block_chunk, -1)
    v_ch = vals.reshape(n_chunks, block_chunk, -1)
    w_ch = w.reshape(n_chunks, block_chunk, -1)

    def step(carry, inputs):
        A, b, n = carry
        br, c, v, ww = inputs
        ww = ww.astype(opposite.dtype)  # int8 wire format -> f32 math
        vecs = opposite[c]  # [CB, D, f] gather
        if implicit:
            ow = ww * (alpha * v)  # (conf - 1), 0 in pad slots
            bw = ww * (1.0 + alpha * v)
        else:
            ow = ww
            bw = ww * v
        A_blk = jnp.einsum("bdf,bdg->bfg", ow[..., None] * vecs, vecs)
        b_blk = jnp.einsum("bd,bdf->bf", bw, vecs)
        n_blk = ww.sum(axis=-1)
        A = A.at[br].add(A_blk, indices_are_sorted=True)
        b = b.at[br].add(b_blk, indices_are_sorted=True)
        n = n.at[br].add(n_blk, indices_are_sorted=True)
        return (A, b, n), None

    (A, b, n), _ = lax.scan(step, (A0, b0, n0), (br_ch, c_ch, v_ch, w_ch))
    return A, b, n


def _batched_spd_solve(A: jnp.ndarray, b: jnp.ndarray, solver: str) -> jnp.ndarray:
    """Solve B independent f-by-f SPD systems. ``cg`` = Jacobi-preconditioned
    conjugate gradient for f+4 iterations (exact termination on the f-dim
    space; batched matvecs ride the MXU — see ALSConfig.solver); ``cholesky``
    = LAPACK-style factorization (reference semantics, slower on TPU)."""
    if solver == "cholesky":
        return jax.scipy.linalg.cho_solve((jnp.linalg.cholesky(A), True), b)
    f = A.shape[-1]
    dinv = 1.0 / jnp.diagonal(A, axis1=-2, axis2=-1)

    def mv(x):
        return jnp.einsum("bij,bj->bi", A, x)

    x = b * dinv
    r = b - mv(x)
    z = r * dinv
    p = z
    rz = jnp.sum(r * z, -1)

    def body(_, st):
        x, r, p, rz = st
        Ap = mv(p)
        alpha = rz / jnp.maximum(jnp.sum(p * Ap, -1), 1e-30)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * Ap
        z = r * dinv
        rz2 = jnp.sum(r * z, -1)
        p = z + (rz2 / jnp.maximum(rz, 1e-30))[:, None] * p
        return x, r, p, rz2

    x, *_ = lax.fori_loop(0, f + 4, body, (x, r, p, rz))
    return x


def _solve_blocked(
    block_rows,
    cols,
    vals,
    w,
    opposite,
    n_entities,
    block_chunk,
    reg,
    implicit,
    alpha,
    degree_scaled_reg: bool,
    solver: str = "cg",
):
    f = opposite.shape[1]
    A, b, counts = _normal_equations_blocked(
        block_rows, cols, vals, w, opposite, n_entities, block_chunk, implicit, alpha
    )
    eye = jnp.eye(f, dtype=opposite.dtype)
    if implicit:
        gram = opposite.T @ opposite
        A = A + gram[None, :, :]
    if degree_scaled_reg:
        A = A + (reg * jnp.maximum(counts, 1.0))[:, None, None] * eye[None, :, :]
    else:
        A = A + reg * eye[None, :, :]
    return _batched_spd_solve(A, b, solver)


def _solve_side(
    rows,
    cols,
    vals,
    opposite,
    n_entities,
    chunk,
    reg,
    implicit,
    alpha,
    degree_scaled_reg: bool = True,
    solver: str = "cg",
):
    f = opposite.shape[1]
    A, b, counts = _normal_equations(
        rows, cols, vals, opposite, n_entities, chunk, implicit, alpha
    )
    eye = jnp.eye(f, dtype=opposite.dtype)
    if implicit:
        gram = opposite.T @ opposite  # shared dense term, one f x f matmul
        A = A + gram[None, :, :]
    if degree_scaled_reg:
        # ALS-WR: λ·n_e·I — degree-invariant conditioning (module docstring)
        scale = jnp.maximum(counts, 1.0)
        A = A + (reg * scale)[:, None, None] * eye[None, :, :]
    else:
        A = A + reg * eye[None, :, :]
    return _batched_spd_solve(A, b, solver)


# One ALS iteration per executable launch — deliberately NOT a fused
# fori_loop over iterations. Round-3 triage of the round-2 bench crash
# found two hard reasons:
#   1. The remote-attach TPU runtime kills any single program execution
#      running longer than ~60s (surfaces as an opaque UNAVAILABLE device
#      fault at the next fetch). At ML-20M scale one iteration is seconds
#      of device time, so a 10-iteration fused loop is guaranteed dead.
#   2. A fused loop with a static trip count gets unrolled by XLA (compile
#      time scales with iterations) and with a traced trip count hides
#      per-iteration progress.
# Host-looped dispatch costs one dispatch RTT per iteration (negligible
# against seconds of device work), keeps every launch far under the
# watchdog, never recompiles when `iterations` changes, and gives the
# trainer natural mid-train checkpoint/convergence hooks. Factors and the
# COO tables stay resident on device across launches.
@functools.partial(
    jax.jit,
    static_argnames=(
        "n_users",
        "n_items",
        "reg",
        "implicit",
        "alpha",
        "block_chunk",
        "degree_scaled_reg",
        "solver",
    ),
    donate_argnums=(0, 1),
)
def _als_step(
    user_factors,
    item_factors,
    u_br,
    u_cols,
    u_vals,
    u_w,
    i_br,
    i_cols,
    i_vals,
    i_w,
    *,
    n_users: int,
    n_items: int,
    reg: float,
    implicit: bool,
    alpha: float,
    block_chunk: int,
    degree_scaled_reg: bool = True,
    solver: str = "cg",
):
    user_factors = _solve_blocked(
        u_br, u_cols, u_vals, u_w, item_factors, n_users + 1, block_chunk,
        reg, implicit, alpha, degree_scaled_reg, solver,
    )
    item_factors = _solve_blocked(
        i_br, i_cols, i_vals, i_w, user_factors, n_items + 1, block_chunk,
        reg, implicit, alpha, degree_scaled_reg, solver,
    )
    return user_factors, item_factors


@functools.partial(jax.jit, static_argnames=("n_users", "n_items", "rank", "seed"))
def _als_init(*, n_users: int, n_items: int, rank: int, seed: int):
    key = jax.random.PRNGKey(seed)
    # +1 dummy row absorbs padding scatters
    item_factors = (
        jax.random.normal(key, (n_items + 1, rank), jnp.float32) / jnp.sqrt(rank)
    )
    user_factors = jnp.zeros((n_users + 1, rank), jnp.float32)
    return user_factors, item_factors


def als_train(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig,
) -> tuple[jax.Array, jax.Array]:
    """Train explicit or implicit ALS; returns (user_factors [n_users, f],
    item_factors [n_items, f])."""
    user_idx = np.asarray(user_idx, np.int32)
    item_idx = np.asarray(item_idx, np.int32)
    ratings = np.asarray(ratings, np.float32)
    valid = (user_idx >= 0) & (item_idx >= 0)
    user_idx, item_idx, ratings = user_idx[valid], item_idx[valid], ratings[valid]
    d = max(8, min(config.block_d, config.chunk))
    block_chunk = max(8, config.chunk // d)

    u_blocks = _block_coo(user_idx, item_idx, ratings, d, block_chunk, n_users)
    i_blocks = _block_coo(item_idx, user_idx, ratings, d, block_chunk, n_items)
    # block tables cross host->device ONCE; the per-iteration launches reuse
    # the same device buffers
    dev = [jax.device_put(a) for a in (*u_blocks, *i_blocks)]
    user_f, item_f = _als_init(
        n_users=n_users, n_items=n_items, rank=config.rank, seed=config.seed
    )
    for _ in range(config.iterations):
        user_f, item_f = _als_step(
            user_f,
            item_f,
            *dev,
            n_users=n_users,
            n_items=n_items,
            reg=config.reg,
            implicit=config.implicit,
            alpha=config.alpha,
            block_chunk=block_chunk,
            degree_scaled_reg=config.degree_scaled_reg,
            solver=config.solver,
        )
    return user_f[:n_users], item_f[:n_items]


# ---------------------------------------------------------------------------
# Serving-side scoring
# ---------------------------------------------------------------------------
#
# The hot path (BASELINE's <10ms p50 target) is engineered for minimum
# host<->device round trips, because on a remote-attached TPU every transfer
# is a network RTT and on a local one every transfer is a dispatch:
#   - factor tables stay resident on device (``ServingIndex``),
#   - the query uploads ONE int32 scalar (the user index); the factor gather
#     happens on device,
#   - scores and indices come back in ONE packed int32 fetch. The scores ride
#     as a bitcast (float32 bits are preserved exactly in an int32 lane);
#     packing the *indices* as float32 would be wrong — small indices bitcast
#     to denormal floats, which XLA flushes to zero.


def _pack(scores, idx):
    return jnp.stack([lax.bitcast_convert_type(scores, jnp.int32), idx])


def _unpack(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    return packed[0].view(np.float32), packed[1]


@functools.partial(jax.jit, static_argnames=("k",))
def _serve_by_index(uidx, user_factors, item_factors, mask, k: int):
    scores = item_factors @ user_factors[uidx]  # [n_items]
    scores = jnp.where(mask, scores, -jnp.inf)
    return _pack(*lax.top_k(scores, k))


@functools.partial(jax.jit, static_argnames=("k",))
def _serve_by_index_batch(uidxs, user_factors, item_factors, mask, k: int):
    scores = user_factors[uidxs] @ item_factors.T  # [B, n_items] on the MXU
    scores = jnp.where(mask[None, :], scores, -jnp.inf)
    s, i = lax.top_k(scores, k)
    return jnp.stack([lax.bitcast_convert_type(s, jnp.int32), i], axis=1)


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_scores_packed(user_vec, item_factors, mask, k: int):
    scores = item_factors @ user_vec
    scores = jnp.where(mask, scores, -jnp.inf)
    return _pack(*lax.top_k(scores, k))


def predict_scores(user_vec: jax.Array, item_factors: jax.Array) -> jax.Array:
    return item_factors @ user_vec


def top_k_items(
    user_vec: jax.Array,
    item_factors: jax.Array,
    k: int,
    mask: jax.Array | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """One-shot top-k for an explicit user vector; single packed fetch.
    ``mask`` False = excluded item. Prefer ``ServingIndex`` on the serving
    path — it also keeps the user table resident."""
    if mask is None:
        mask = jnp.ones((item_factors.shape[0],), bool)
    packed = np.asarray(_topk_scores_packed(user_vec, item_factors, mask, k))
    return _unpack(packed)


def next_pow2(n: int) -> int:
    """Bucket-rounding rule shared by the batched predict path and
    ``ServingIndex.warmup_buckets`` — they must agree or warmed shapes won't
    match served shapes and serve-time compiles come back."""
    return 1 << max(0, n - 1).bit_length()


class ServingIndex:
    """Device-resident factor tables with index-addressed top-k serve.

    The TPU replacement for the reference's in-JVM model broadcast
    (``CreateServer.scala:196-200`` deserializes the kryo model into the
    server heap; here the model lives in HBM and every query is one compiled
    kernel). Per-query cost: one int32 upload + one [2,k] int32 fetch
    (row 0 = float32 score bits, row 1 = item indices).
    """

    def __init__(self, user_factors, item_factors):
        self.user_factors = jnp.asarray(user_factors)
        self.item_factors = jnp.asarray(item_factors)
        self._full_mask = jnp.ones((self.item_factors.shape[0],), bool)

    @property
    def n_users(self) -> int:
        return self.user_factors.shape[0]

    @property
    def n_items(self) -> int:
        return self.item_factors.shape[0]

    def warmup(self, k: int) -> None:
        jax.block_until_ready(
            _serve_by_index(
                jnp.int32(0), self.user_factors, self.item_factors, self._full_mask, k
            )
        )

    def warmup_buckets(self, k: int, max_batch: int) -> None:
        """Pre-compile every power-of-two batch bucket up to ``max_batch``
        for top-``k`` (k rounded up to its own bucket). The batched predict
        path buckets ragged batch sizes to powers of two; compiling them all
        at deploy time keeps the first ragged burst from paying a compile."""
        kk = min(next_pow2(k), self.n_items)
        b = 1
        handles = []
        # the dispatch path buckets len(batch) <= max_batch up to
        # next_pow2(max_batch), so that is the range to warm (warming only
        # to max_batch would leave e.g. bucket 128 cold for max_batch=100)
        while b <= next_pow2(max_batch):
            handles.append(
                _serve_by_index_batch(
                    jnp.zeros((b,), jnp.int32),
                    self.user_factors,
                    self.item_factors,
                    self._full_mask,
                    kk,
                )
            )
            b *= 2
        jax.block_until_ready(handles)

    def serve(
        self, user_index: int, k: int, mask: jax.Array | np.ndarray | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-k (scores, item indices) for one user index."""
        m = self._full_mask if mask is None else jnp.asarray(mask)
        packed = np.asarray(
            _serve_by_index(
                jnp.int32(user_index), self.user_factors, self.item_factors, m, k
            )
        )
        return _unpack(packed)

    def serve_batch(
        self,
        user_indices: np.ndarray,
        k: int,
        mask: jax.Array | np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Micro-batched serve: [B] indices -> ([B,k] scores, [B,k] items).
        This is the throughput path an async query server batches into."""
        return self.unpack_batch(
            np.asarray(self.serve_batch_async(user_indices, k, mask))
        )

    def serve_batch_async(
        self,
        user_indices: np.ndarray | jax.Array,
        k: int,
        mask: jax.Array | np.ndarray | None = None,
    ) -> jax.Array:
        """Non-blocking batched serve: dispatches the kernel and returns the
        packed [B,2,k] int32 device array WITHOUT fetching it. An async query
        server dispatches batch n+1 while fetching batch n's result, so
        device work and transport overlap; decode with ``unpack_batch``."""
        m = self._full_mask if mask is None else jnp.asarray(mask)
        if isinstance(user_indices, jax.Array):
            # already on device: a np.asarray round-trip would block on a
            # D2H fetch and defeat the non-blocking contract
            idxs = user_indices.astype(jnp.int32)
        else:
            idxs = jnp.asarray(np.asarray(user_indices, np.int32))
        return _serve_by_index_batch(
            idxs, self.user_factors, self.item_factors, m, k
        )

    @staticmethod
    def unpack_batch(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Decode a fetched [B,2,k] packed result into ([B,k] float32 scores,
        [B,k] int32 item indices)."""
        return (
            np.ascontiguousarray(packed[:, 0, :]).view(np.float32),
            packed[:, 1, :],
        )
