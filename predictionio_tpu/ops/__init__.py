"""TPU compute kernels: ALS solvers, top-k retrieval, cooccurrence counting.

These replace the reference's use of Spark MLlib (``ALS.train`` /
``trainImplicit`` in the recommendation templates, cosine similarity in
similar-product, NaiveBayes in classification) with XLA-compiled JAX on
sharded arrays.
"""

from predictionio_tpu.ops.als import (
    ALSConfig,
    ServingIndex,
    als_train,
    predict_scores,
    top_k_items,
)

__all__ = ["ALSConfig", "ServingIndex", "als_train", "predict_scores", "top_k_items"]
