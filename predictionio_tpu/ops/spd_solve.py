"""Batched small-SPD solve with a VMEM-resident fused-CG pallas kernel.

The ALS half-solve ends with ~n_entities independent [f, f] SPD systems
(f = rank, 16-64). The stock path (``ops/als.py:_batched_spd_solve``)
runs Jacobi-preconditioned CG for f+4 iterations as whole-array jnp ops:
every iteration re-reads the entire [n, f, f] A tensor from HBM — at
ML-20M that is 36 passes over ~680 MB per side, ~70% of the iteration's
mandatory memory traffic (docs/PERF.md round-5 HBM model).

This kernel runs the IDENTICAL algorithm — same preconditioner, same
f+4 exact-termination iteration count, same update order, so results
match to float rounding — but tiles A into VMEM once and keeps every CG
vector on-chip: HBM traffic drops to one read of A + the vectors, and
the per-iteration matvecs become MXU ``dot_general``s over the resident
tile. One pallas grid cell handles ``bs`` systems ([bs, f, f] ≈ 0.5 MB
at bs=128, f=32).

Reference analog: the per-entity normal-equation solves inside MLlib
ALS (``CholeskySolver`` in the reference's Spark stack); redesigned
TPU-first rather than translated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _cg_body(A, b, iters: int, *, unroll: bool = True):
    """THE Jacobi-CG used everywhere: ops/als.py's stock ``cg`` branch
    calls this with ``unroll=False`` (lax.fori_loop — small HLO for the
    whole-array path) and the pallas kernel with ``unroll=True`` (static
    trip count inside the grid cell). One shared body means the fused
    kernel's 'identical algorithm' parity contract cannot silently drift."""
    f = A.shape[-1]
    eye = jnp.eye(f, dtype=A.dtype)
    dinv = 1.0 / jnp.sum(A * eye, axis=-1)  # diagonal without jnp.diagonal

    def mv(x):
        return jax.lax.dot_general(
            A, x[..., None], (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )[..., 0]

    def step(st):
        x, r, p, rz = st
        Ap = mv(p)
        alpha = rz / jnp.maximum(jnp.sum(p * Ap, -1), 1e-30)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * Ap
        z = r * dinv
        rz2 = jnp.sum(r * z, -1)
        p = z + (rz2 / jnp.maximum(rz, 1e-30))[:, None] * p
        return x, r, p, rz2

    x = b * dinv
    r = b - mv(x)
    z = r * dinv
    st = (x, r, z, jnp.sum(r * z, -1))
    if unroll:
        for _ in range(iters):
            st = step(st)
    else:
        st = jax.lax.fori_loop(0, iters, lambda _, s: step(s), st)
    return st[0]


def _kernel(a_ref, b_ref, x_ref, *, iters: int):
    x_ref[...] = _cg_body(a_ref[...], b_ref[...], iters)


@functools.partial(jax.jit, static_argnames=("bs", "interpret"))
def batched_spd_solve_fused(
    A: jnp.ndarray,  # [n, f, f] SPD (regularized normal equations)
    b: jnp.ndarray,  # [n, f]
    bs: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Solve n independent SPD systems; one HBM read of A total.

    Pads n up to a multiple of ``bs`` with identity systems (solution 0)
    — the pad rows are sliced off before returning.
    """
    from jax.experimental import pallas as pl

    n, f = A.shape[0], A.shape[-1]
    iters = f + 4
    pad = (-n) % bs
    if pad:
        eye = jnp.broadcast_to(jnp.eye(f, dtype=A.dtype), (pad, f, f))
        A = jnp.concatenate([A, eye])
        b = jnp.concatenate([b, jnp.zeros((pad, f), b.dtype)])
    n_pad = A.shape[0]
    out = pl.pallas_call(
        functools.partial(_kernel, iters=iters),
        grid=(n_pad // bs,),
        in_specs=[
            pl.BlockSpec((bs, f, f), lambda i: (i, 0, 0)),
            pl.BlockSpec((bs, f), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bs, f), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, f), jnp.float32),
        interpret=interpret,
    )(A.astype(jnp.float32), b.astype(jnp.float32))
    return out[:n]


def batched_spd_solve_auto(A: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Fused kernel on TPU; the identical-algorithm jnp path elsewhere
    (same platform-sniff contract as ops/attention.fused_attention)."""
    if jax.default_backend() in ("tpu", "axon"):
        return batched_spd_solve_fused(A, b)
    return _cg_body(A, b, A.shape[-1] + 4, unroll=False)
