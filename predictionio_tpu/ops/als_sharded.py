"""Multi-device ALS: ALX-style sharded alternating least squares.

This is the TPU answer to SURVEY.md section 7 hard part (a) — the reference
scales ALS through MLlib's shuffle joins of factor blocks across Spark
executors (``ALSAlgorithm.scala:79-85`` calls into MLlib; MLlib partitions
user/item blocks and shuffles per iteration). Here the same computation is
laid out for an ICI mesh the way ALX (PAPERS.md) does:

  - Users and items are partitioned into one contiguous block per device
    along the mesh axis; each device owns its block's factors for the whole
    run (no resharding between iterations).
  - Ratings are partitioned twice on the host: by owning user block (for
    the user-side solve) and by owning item block (for the item-side
    solve) — the moral equivalent of MLlib's two pre-shuffled COO layouts,
    done once, not per iteration.
  - Each half-iteration ``all_gather``s the *opposite* side's factor blocks
    over ICI (the only cross-device traffic, f * n_opposite * 4 bytes),
    builds per-entity normal equations from the local COO shard with
    static-shape chunked scatter-adds, and solves its own block's f-by-f
    systems batched (Cholesky on the MXU).
  - Shapes are identical on every device (blocks and COO shards are padded;
    padding scatters land in a per-block dummy row). Each iteration is ONE
    ``shard_map`` launch (host-looped, like ``ops/als.py:_als_step``): the
    remote-attach TPU runtime kills single executions past ~60s, and
    per-iteration dispatch costs one RTT against seconds of device work.

Communication per iteration: 2 all_gathers (U and V). MLlib pays 2 shuffles
of the *rating* table per iteration, which is strictly larger for any
realistic nnz >> entities * f.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops.als import (
    ALSConfig,
    _compress_ratings_wire,
    _host_group_by,
    _pad_blocks,
    _solve_blocked,
)

try:  # stable home since jax 0.8
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

import inspect

# the replication/varying checker kwarg was renamed check_rep -> check_vma
_NO_CHECK = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(shard_map).parameters
    else {"check_rep": False}
)


def _block_partition_blocked(
    owner_idx: np.ndarray,
    other_idx: np.ndarray,
    vals: np.ndarray,
    block: int,
    n_dev: int,
    d: int,
    block_chunk: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Split COO by owning device block, localize owner indices, and pack
    each device's shard into the ALX entity-block layout (the same MXU
    Gram formulation the single-chip path uses). All devices are padded to
    one common block count with dummy blocks (local dummy row = ``block``).

    One global O(n) group-by (native C++ counting sort — device blocks are
    contiguous entity ranges, so grouping by entity also groups by device)
    replaces the per-device stable argsorts this used to run: at ML-20M on
    8 devices that was 16 argsorts over the full rating list per train.
    The within-entity order (original event order) and the emitted layout
    are identical to the old packer's.

    Returns stacked [n_dev, NB], [n_dev, NB, d] x2, [n_dev, NB, d] arrays.
    """
    n_ent = n_dev * block
    cols_g, vals_g, deg = _host_group_by(
        owner_idx.astype(np.int32),
        other_idx.astype(np.int32),
        vals.astype(np.float32),
        n_ent,
    )
    start = np.concatenate([[0], np.cumsum(deg)])
    nblk = -(-deg // d)  # blocks per entity (0 for unrated entities)
    per_dev_blocks = nblk.reshape(n_dev, block).sum(axis=1)
    nb = _pad_blocks(int(per_dev_blocks.max()), block_chunk)
    br = np.full((n_dev, nb), block, np.int32)
    cols = np.zeros((n_dev, nb, d), np.int32)
    v = np.zeros((n_dev, nb, d), np.float32)
    w = np.zeros((n_dev, nb, d), np.int8)
    for dev in range(n_dev):
        e0, e1 = dev * block, (dev + 1) * block
        deg_l = deg[e0:e1]
        r0, r1 = int(start[e0]), int(start[e1])
        if r1 == r0:
            continue  # no ratings for this device's entities
        nblk_l = nblk[e0:e1]
        block_base = np.concatenate([[0], np.cumsum(nblk_l)])
        # position of each grouped row within its entity -> (block, slot)
        p = np.arange(r1 - r0) - np.repeat(start[e0:e1] - r0, deg_l)
        eidx = np.repeat(np.arange(block), deg_l)
        cols[dev, block_base[eidx] + p // d, p % d] = cols_g[r0:r1]
        v[dev, block_base[eidx] + p // d, p % d] = vals_g[r0:r1]
        w[dev, block_base[eidx] + p // d, p % d] = 1
        br[dev, : int(block_base[-1])] = np.repeat(np.arange(block), nblk_l)
    return br, cols, v, w


@functools.partial(jax.jit, static_argnames=("sharding",))
def _decode_ratings(codes, table, sharding):
    """One sharded gather decoding the uint8 dictionary ratings wire
    (module-level jit: compiles once per shape, not per train)."""
    return jax.lax.with_sharding_constraint(
        table[codes.astype(jnp.int32)], sharding
    )


def als_train_sharded(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig,
    mesh: Mesh | None = None,
    axis: str = "data",
) -> tuple[np.ndarray, np.ndarray]:
    """ALS over a device mesh; returns host numpy (user_factors,
    item_factors) exactly shaped [n_users, f] / [n_items, f].

    ``mesh`` defaults to a 1-D mesh over all visible devices. With one
    device this degrades gracefully to the single-chip schedule.
    """
    from predictionio_tpu.obs import xray

    prof = xray.current_profile()
    if mesh is None:
        # pio-lint: disable=train-unaccounted-sync -- host-side device list, not a device fetch
        mesh = Mesh(np.asarray(jax.devices()), (axis,))
    n_dev = mesh.shape[axis]

    with xray.phase(xray.PHASE_HOST_ETL):
        user_idx = np.asarray(user_idx, np.int32)
        item_idx = np.asarray(item_idx, np.int32)
        ratings = np.asarray(ratings, np.float32)
        valid = (user_idx >= 0) & (item_idx >= 0)
        user_idx, item_idx, ratings = (
            user_idx[valid], item_idx[valid], ratings[valid]
        )

        bu = max(1, -(-n_users // n_dev))  # users per device block
        bi = max(1, -(-n_items // n_dev))
        d = max(8, min(config.block_d, config.chunk))
        block_chunk = max(8, config.chunk // d)

        u_blocks = _block_partition_blocked(
            user_idx, item_idx, ratings, bu, n_dev, d, block_chunk
        )
        i_blocks = _block_partition_blocked(
            item_idx, user_idx, ratings, bi, n_dev, d, block_chunk
        )

        spec = P(axis)
        sharded = NamedSharding(mesh, spec)
        put = lambda x: jax.device_put(x, sharded)

        statics = dict(
            mesh=mesh,
            axis=axis,
            bu=bu,
            bi=bi,
            rank=config.rank,
            reg=config.reg,
            implicit=config.implicit,
            alpha=config.alpha,
            block_chunk=block_chunk,
            degree_scaled_reg=config.degree_scaled_reg,
            solver=config.solver,
            gather_dtype=config.gather_dtype,
        )
        def put_vals(v: np.ndarray):
            """Upload a [n_dev, nb, d] ratings table in its smallest LOSSLESS
            form: uint8 dictionary codes + a tiny replicated value table,
            decoded once on device by a sharded gather (same contract as the
            single-chip wire — every star-rating dataset fits; pad zeros join
            the dictionary). Falls back to the full f32 table otherwise."""
            codes, table = _compress_ratings_wire(v.reshape(-1))
            if table is None or codes.dtype != np.uint8:
                return put(v)
            return _decode_ratings(
                put(codes.reshape(v.shape)), jax.device_put(table), sharded
            )

        u_br, u_cols, u_v, u_w = u_blocks
        i_br, i_cols, i_v, i_w = i_blocks
        dev = (
            put(u_br), put(u_cols), put_vals(u_v), put(u_w),
            put(i_br), put(i_cols), put_vals(i_v), put(i_w),
        )
        # one iteration per launch — same watchdog/compile rationale as
        # ops/als.py:_als_step; collectives still ride ICI inside each launch
        uf, vf = _als_sharded_init(
            mesh=mesh, axis=axis, bu=bu, bi=bi, rank=config.rank,
            seed=config.seed, n_items=n_items,
        )
    import contextlib

    nnz = int(user_idx.shape[0])
    for _ in range(config.iterations):
        with contextlib.ExitStack() as stack:
            rec = (
                stack.enter_context(prof.step(nnz=nnz, mesh=str(dict(mesh.shape))))
                if prof is not None
                else None
            )
            with xray.phase(xray.PHASE_SWEEP):
                uf, vf = _als_sharded_step(uf, vf, *dev, **statics)
                if rec is not None:
                    rec["metric"] = prof.device_barrier(
                        uf, vf, where="als-sharded-sweep"
                    )
        if prof is not None:
            with prof.phase(xray.PHASE_HOST_ETL):
                prof.add_rows(nnz)
                prof.sample_memory()
    # [n_dev, b+1, f] -> drop per-block dummy row, concatenate, trim padding
    with xray.phase(xray.PHASE_HOST_ETL):
        uf = _fetch(uf).reshape(n_dev, bu + 1, config.rank)[:, :bu].reshape(
            -1, config.rank
        )
        vf = _fetch(vf).reshape(n_dev, bi + 1, config.rank)[:, :bi].reshape(
            -1, config.rank
        )
    return uf[:n_users], vf[:n_items]


def _fetch(a) -> np.ndarray:
    """Device -> host, gathering across processes when the mesh spans hosts
    (a multi-host sharded array is not addressable from any single host).
    The final fetch rides ``obs.xray.device_fetch`` so a profiled sharded
    train accounts its readback stall like every other device wait."""
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.experimental import multihost_utils

        a = multihost_utils.process_allgather(a, tiled=True)
    from predictionio_tpu.obs import xray

    return xray.device_fetch(a, where="als-sharded-fetch")


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "bu", "bi", "rank", "seed", "n_items"),
)
def _als_sharded_init(
    *, mesh: Mesh, axis: str, bu: int, bi: int, rank: int, seed: int, n_items: int
):
    spec = P(axis)

    def device_fn():
        d = lax.axis_index(axis)
        # per-device init of the owned item block (+ dummy row)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), d)
        vf_local = jax.random.normal(key, (bi + 1, rank), jnp.float32) / jnp.sqrt(
            rank
        )
        # zero padding rows whose global index >= n_items so they don't bias
        # the implicit-mode gram term in the first user-side solve (they only
        # self-zero after the first item solve otherwise)
        global_row = d * bi + jnp.arange(bi + 1)
        vf_local = jnp.where((global_row < n_items)[:, None], vf_local, 0.0)
        uf_local = jnp.zeros((bu + 1, rank), jnp.float32)
        # leading device axis for the P(axis) out_spec
        return uf_local[None], vf_local[None]

    return shard_map(
        device_fn, mesh=mesh, in_specs=(), out_specs=(spec, spec), **_NO_CHECK
    )()


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "axis",
        "bu",
        "bi",
        "rank",
        "reg",
        "implicit",
        "alpha",
        "block_chunk",
        "degree_scaled_reg",
        "solver",
        "gather_dtype",
    ),
    donate_argnums=(0, 1),
)
def _als_sharded_step(
    uf,
    vf,
    u_br,
    u_cols,
    u_vals,
    u_w,
    i_br,
    i_cols,
    i_vals,
    i_w,
    *,
    mesh: Mesh,
    axis: str,
    bu: int,
    bi: int,
    rank: int,
    reg: float,
    implicit: bool,
    alpha: float,
    block_chunk: int,
    degree_scaled_reg: bool = True,
    solver: str = "cg",
    gather_dtype: str = "f32",
):
    spec = P(axis)

    def device_fn(uf_l, vf_l, u_br, u_cols, u_vals, u_w, i_br, i_cols, i_vals, i_w):
        # shard_map hands each device its [1, ...] slice; flatten it
        uf_l, vf_l = uf_l[0], vf_l[0]
        n_dev = lax.psum(1, axis)

        # bf16 across the ICI only in EXPLICIT mode: it halves the
        # collective bytes and hands _solve_blocked the same bf16 rows the
        # single-chip path gathers (its accumulators stay f32 — see
        # _normal_equations_blocked). Implicit mode gathers f32 so the
        # shared V^T V gram term is computed from full-precision factors,
        # exactly like the single-chip bf16 path (which rounds ONLY the
        # per-row gathers, never the gram input).
        wire_bf16 = gather_dtype == "bf16" and not implicit

        def gather_side(local, block):
            # [n_dev, block+1, f] -> drop dummies -> [n_dev*block, f]
            send = local.astype(jnp.bfloat16) if wire_bf16 else local
            full = lax.all_gather(send, axis)  # ICI collective
            return full[:, :block].reshape(n_dev * block, rank)

        # per-device dummy-block padding means pads inflate only the local
        # dummy row's degree count, so ALS-WR scaling stays exact; the local
        # solve is the same MXU block-Gram path as the single-chip schedule
        v_full = gather_side(vf_l, bi)
        uf_l = _solve_blocked(
            u_br[0], u_cols[0], u_vals[0], u_w[0], v_full, bu + 1,
            block_chunk, reg, implicit, alpha, degree_scaled_reg, solver,
            gather_dtype,
        )
        u_full = gather_side(uf_l, bu)
        vf_l = _solve_blocked(
            i_br[0], i_cols[0], i_vals[0], i_w[0], u_full, bi + 1,
            block_chunk, reg, implicit, alpha, degree_scaled_reg, solver,
            gather_dtype,
        )
        return uf_l[None], vf_l[None]

    # checker off: the scan carries inside the block-Gram accumulation are
    # initialized unvarying (zeros) and become device-varying on the first
    # write, which the varying-manual-axes checker rejects; semantics are
    # unaffected
    return shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(spec,) * 10,
        out_specs=(spec, spec),
        **_NO_CHECK,
    )(uf, vf, u_br, u_cols, u_vals, u_w, i_br, i_cols, i_vals, i_w)
