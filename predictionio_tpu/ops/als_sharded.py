"""Multi-device ALS: ALX-style sharded alternating least squares.

This is the TPU answer to SURVEY.md section 7 hard part (a) — the reference
scales ALS through MLlib's shuffle joins of factor blocks across Spark
executors (``ALSAlgorithm.scala:79-85`` calls into MLlib; MLlib partitions
user/item blocks and shuffles per iteration). Here the same computation is
laid out for an ICI mesh the way ALX (PAPERS.md) does:

  - Users and items are partitioned into one contiguous block per device
    along the mesh axis; each device owns its block's factors for the whole
    run (no resharding between iterations).
  - Ratings are partitioned twice on the host: by owning user block (for
    the user-side solve) and by owning item block (for the item-side
    solve) — the moral equivalent of MLlib's two pre-shuffled COO layouts,
    done once, not per iteration.
  - Each half-iteration ``all_gather``s the *opposite* side's factor blocks
    over ICI (the only cross-device traffic, f * n_opposite * 4 bytes),
    builds per-entity normal equations from the local COO shard with
    static-shape chunked scatter-adds, and solves its own block's f-by-f
    systems batched (Cholesky on the MXU).
  - Shapes are identical on every device (blocks and COO shards are padded;
    padding scatters land in a per-block dummy row). Each iteration is ONE
    ``shard_map`` launch (host-looped, like ``ops/als.py:_als_step``): the
    remote-attach TPU runtime kills single executions past ~60s, and
    per-iteration dispatch costs one RTT against seconds of device work.

Communication per iteration: 2 all_gathers (U and V). MLlib pays 2 shuffles
of the *rating* table per iteration, which is strictly larger for any
realistic nnz >> entities * f.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops.als import ALSConfig, _solve_side

try:  # stable home since jax 0.8
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

import inspect

# the replication/varying checker kwarg was renamed check_rep -> check_vma
_NO_CHECK = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(shard_map).parameters
    else {"check_rep": False}
)


def _block_partition_coo(
    owner_idx: np.ndarray,
    other_idx: np.ndarray,
    vals: np.ndarray,
    block: int,
    n_blocks: int,
    chunk: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Split COO by owning block of ``owner_idx``; localize owner indices to
    the block; pad every shard to one common chunk-multiple length with
    scatters into the per-block dummy row (local index ``block``).

    Returns [n_blocks, L] arrays (owner-local rows, other-global cols, vals).
    """
    owners = owner_idx // block
    per_dev = [np.flatnonzero(owners == d) for d in range(n_blocks)]
    longest = max((len(ix) for ix in per_dev), default=0)
    length = max(chunk, ((longest + chunk - 1) // chunk) * chunk)
    rows = np.full((n_blocks, length), block, np.int32)  # dummy local row
    cols = np.zeros((n_blocks, length), np.int32)
    v = np.zeros((n_blocks, length), np.float32)
    for d, ix in enumerate(per_dev):
        rows[d, : len(ix)] = (owner_idx[ix] - d * block).astype(np.int32)
        cols[d, : len(ix)] = other_idx[ix].astype(np.int32)
        v[d, : len(ix)] = vals[ix].astype(np.float32)
    return rows, cols, v


def als_train_sharded(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig,
    mesh: Mesh | None = None,
    axis: str = "data",
) -> tuple[np.ndarray, np.ndarray]:
    """ALS over a device mesh; returns host numpy (user_factors,
    item_factors) exactly shaped [n_users, f] / [n_items, f].

    ``mesh`` defaults to a 1-D mesh over all visible devices. With one
    device this degrades gracefully to the single-chip schedule.
    """
    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()), (axis,))
    n_dev = mesh.shape[axis]

    user_idx = np.asarray(user_idx, np.int32)
    item_idx = np.asarray(item_idx, np.int32)
    ratings = np.asarray(ratings, np.float32)
    valid = (user_idx >= 0) & (item_idx >= 0)
    user_idx, item_idx, ratings = user_idx[valid], item_idx[valid], ratings[valid]

    bu = max(1, -(-n_users // n_dev))  # users per device block
    bi = max(1, -(-n_items // n_dev))
    chunk = min(
        config.chunk,
        max(256, 1 << int(np.ceil(np.log2(max(1, len(ratings) // max(1, n_dev)))))),
    )

    u_rows, u_cols, u_vals = _block_partition_coo(
        user_idx, item_idx, ratings, bu, n_dev, chunk
    )
    i_rows, i_cols, i_vals = _block_partition_coo(
        item_idx, user_idx, ratings, bi, n_dev, chunk
    )

    spec = P(axis)
    sharded = NamedSharding(mesh, spec)
    put = lambda x: jax.device_put(x, sharded)

    statics = dict(
        mesh=mesh,
        axis=axis,
        bu=bu,
        bi=bi,
        rank=config.rank,
        reg=config.reg,
        implicit=config.implicit,
        alpha=config.alpha,
        chunk=chunk,
        degree_scaled_reg=config.degree_scaled_reg,
        solver=config.solver,
    )
    dev = (
        put(u_rows),
        put(u_cols),
        put(u_vals),
        put(i_rows),
        put(i_cols),
        put(i_vals),
    )
    # one iteration per launch — same watchdog/compile rationale as
    # ops/als.py:_als_step; collectives still ride ICI inside each launch
    uf, vf = _als_sharded_init(
        mesh=mesh, axis=axis, bu=bu, bi=bi, rank=config.rank,
        seed=config.seed, n_items=n_items,
    )
    for _ in range(config.iterations):
        uf, vf = _als_sharded_step(uf, vf, *dev, **statics)
    # [n_dev, b+1, f] -> drop per-block dummy row, concatenate, trim padding
    uf = _fetch(uf).reshape(n_dev, bu + 1, config.rank)[:, :bu].reshape(-1, config.rank)
    vf = _fetch(vf).reshape(n_dev, bi + 1, config.rank)[:, :bi].reshape(-1, config.rank)
    return uf[:n_users], vf[:n_items]


def _fetch(a) -> np.ndarray:
    """Device -> host, gathering across processes when the mesh spans hosts
    (a multi-host sharded array is not addressable from any single host)."""
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.experimental import multihost_utils

        a = multihost_utils.process_allgather(a, tiled=True)
    return np.asarray(a)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "bu", "bi", "rank", "seed", "n_items"),
)
def _als_sharded_init(
    *, mesh: Mesh, axis: str, bu: int, bi: int, rank: int, seed: int, n_items: int
):
    spec = P(axis)

    def device_fn():
        d = lax.axis_index(axis)
        # per-device init of the owned item block (+ dummy row)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), d)
        vf_local = jax.random.normal(key, (bi + 1, rank), jnp.float32) / jnp.sqrt(
            rank
        )
        # zero padding rows whose global index >= n_items so they don't bias
        # the implicit-mode gram term in the first user-side solve (they only
        # self-zero after the first item solve otherwise)
        global_row = d * bi + jnp.arange(bi + 1)
        vf_local = jnp.where((global_row < n_items)[:, None], vf_local, 0.0)
        uf_local = jnp.zeros((bu + 1, rank), jnp.float32)
        # leading device axis for the P(axis) out_spec
        return uf_local[None], vf_local[None]

    return shard_map(
        device_fn, mesh=mesh, in_specs=(), out_specs=(spec, spec), **_NO_CHECK
    )()


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "axis",
        "bu",
        "bi",
        "rank",
        "reg",
        "implicit",
        "alpha",
        "chunk",
        "degree_scaled_reg",
        "solver",
    ),
    donate_argnums=(0, 1),
)
def _als_sharded_step(
    uf,
    vf,
    u_rows,
    u_cols,
    u_vals,
    i_rows,
    i_cols,
    i_vals,
    *,
    mesh: Mesh,
    axis: str,
    bu: int,
    bi: int,
    rank: int,
    reg: float,
    implicit: bool,
    alpha: float,
    chunk: int,
    degree_scaled_reg: bool = True,
    solver: str = "cg",
):
    spec = P(axis)

    def device_fn(uf_l, vf_l, u_rows, u_cols, u_vals, i_rows, i_cols, i_vals):
        # shard_map hands each device its [1, ...] slice; flatten it
        uf_l, vf_l = uf_l[0], vf_l[0]
        u_r, u_c, u_v = u_rows[0], u_cols[0], u_vals[0]
        i_r, i_c, i_v = i_rows[0], i_cols[0], i_vals[0]
        n_dev = lax.psum(1, axis)

        def gather_side(local, block):
            # [n_dev, block+1, f] -> drop dummies -> [n_dev*block, f]
            full = lax.all_gather(local, axis)  # ICI collective
            return full[:, :block].reshape(n_dev * block, rank)

        # per-block-dummy padding means the COO pads inflate only the dummy
        # row's degree count, so _solve_side's ALS-WR scaling stays exact
        v_full = gather_side(vf_l, bi)
        uf_l = _solve_side(
            u_r, u_c, u_v, v_full, bu + 1, chunk, reg, implicit, alpha,
            degree_scaled_reg, solver,
        )
        u_full = gather_side(uf_l, bu)
        vf_l = _solve_side(
            i_r, i_c, i_v, u_full, bi + 1, chunk, reg, implicit, alpha,
            degree_scaled_reg, solver,
        )
        return uf_l[None], vf_l[None]

    # checker off: the scan carries inside _normal_equations are initialized
    # unvarying (zeros) and become device-varying on the first write, which
    # the varying-manual-axes checker rejects; semantics are unaffected
    return shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(spec, spec),
        **_NO_CHECK,
    )(uf, vf, u_rows, u_cols, u_vals, i_rows, i_cols, i_vals)
