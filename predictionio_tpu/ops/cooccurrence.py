"""Item cooccurrence counting.

Reference parity: ``examples/scala-parallel-similarproduct/
multi-events-multi-algos/src/main/scala/CooccurrenceAlgorithm.scala:30-90``
— distinct (user, item) interactions, per-user ordered item pairs, pair
counts, top-N cooccurring items kept per item. The Spark self-join becomes a
numpy bincount over pair codes.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def cooccurrence_top_n(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    n_items: int,
    top_n: int,
) -> dict[int, list[tuple[int, int]]]:
    """Returns item -> [(other_item, count)] sorted by count desc, len<=top_n.

    Two formulations, fastest first:

    - native (``pio_cooccur_topn``): per-user pair increments into a dense
      count row (fits L1 for ML-scale vocabs) + C++ top-N select — the
      whole ML-1M build lands well under the 300 ms bench gate;
    - scipy fallback: with A the distinct binary user x item interaction
      matrix, ``A.T @ A`` is the full cooccurrence count matrix (diagonal
      = item popularity, zeroed out).
    """
    from scipy import sparse

    from predictionio_tpu.utils.native import cooccur_topn

    u = np.asarray(user_idx, np.int64)
    it = np.asarray(item_idx, np.int64)
    if len(u) == 0:
        return {}
    # distinct (user, item) via 1-D codes — np.unique(axis=0) does a
    # structured-void sort that is ~50x slower at ML-1M scale. The sorted
    # codes come back grouped by user with items ascending within a user:
    # exactly the native kernel's input contract.
    codes = np.unique(u * n_items + it)
    users, items = codes // n_items, codes % n_items
    native = cooccur_topn(users, items, n_items, top_n)
    if native is not None:
        out_items, out_counts = native
        n_valid = (out_items >= 0).sum(axis=1)  # -1 padding is a tail
        items_l = out_items.tolist()
        counts_l = out_counts.tolist()
        out: dict[int, list[tuple[int, int]]] = {}
        for item, nv in enumerate(n_valid.tolist()):
            if nv:
                out[item] = list(zip(items_l[item][:nv], counts_l[item][:nv]))
        return out
    n_users = int(users.max()) + 1
    A = sparse.csr_matrix(
        (np.ones(len(users), np.int64), (users, items)),
        shape=(n_users, n_items),
    )
    C = (A.T @ A).tocsr()
    C.setdiag(0)
    C.eliminate_zeros()
    out: dict[int, list[tuple[int, int]]] = {}
    indptr, indices, data = C.indptr, C.indices, C.data
    for item in range(n_items):
        lo, hi = indptr[item], indptr[item + 1]
        if lo == hi:
            continue
        row_items = indices[lo:hi]
        row_counts = data[lo:hi]
        order = np.lexsort((row_items, -row_counts))[:top_n]
        out[int(item)] = [
            (int(row_items[j]), int(row_counts[j])) for j in order
        ]
    return out


def _cooccurrence_top_n_reference(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    n_items: int,
    top_n: int,
) -> dict[int, list[tuple[int, int]]]:
    """Direct pair-expansion formulation kept as the oracle for tests."""
    pairs = np.unique(
        np.stack([np.asarray(user_idx, np.int64), np.asarray(item_idx, np.int64)], 1),
        axis=0,
    )
    users, items = pairs[:, 0], pairs[:, 1]
    order = np.argsort(users, kind="stable")
    users, items = users[order], items[order]
    # per-user slices
    boundaries = np.flatnonzero(np.diff(users)) + 1
    groups = np.split(items, boundaries)
    codes: list[np.ndarray] = []
    for g in groups:
        if len(g) < 2:
            continue
        a, b = np.meshgrid(g, g, indexing="ij")
        mask = a != b
        codes.append(a[mask] * n_items + b[mask])
    if not codes:
        return {}
    counts = np.bincount(np.concatenate(codes), minlength=0)
    nz = np.flatnonzero(counts)
    out: dict[int, list[tuple[int, int]]] = {}
    lhs = nz // n_items
    rhs = nz % n_items
    cnt = counts[nz]
    order = np.lexsort((-cnt, lhs))
    for i in order:
        item = int(lhs[i])
        bucket = out.setdefault(item, [])
        if len(bucket) < top_n:
            bucket.append((int(rhs[i]), int(cnt[i])))
    return out


def score_by_cooccurrence(
    top_map: dict[int, list[tuple[int, int]]],
    query_items: Sequence[int],
) -> dict[int, float]:
    """Sum cooccurrence counts over the query items (ref predict :70-90)."""
    scores: dict[int, float] = {}
    for qi in query_items:
        for item, count in top_map.get(qi, []):
            scores[item] = scores.get(item, 0.0) + count
    return scores
