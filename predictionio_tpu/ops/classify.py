"""Classification kernels: multinomial naive Bayes (jax) and a compact
random forest (numpy).

These back the classification template, replacing MLlib's ``NaiveBayes.train``
and ``RandomForest.trainClassifier`` (ref
``examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala`` / ``RandomForestAlgorithm.scala``).

The NB train/score paths are jit-compiled batched matmuls (MXU-friendly);
the forest is a host-side structure whose batched inference is vectorized
per tree.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Multinomial naive Bayes (MLlib-compatible semantics)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NaiveBayesModel:
    labels: np.ndarray  # [C] class label values
    log_priors: np.ndarray  # [C]
    log_theta: np.ndarray  # [C, F] feature log-probabilities

    def predict(self, features: np.ndarray) -> float:
        scores = self.log_priors + self.log_theta @ np.asarray(features, np.float64)
        return float(self.labels[int(np.argmax(scores))])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        scores = _nb_scores(
            jnp.asarray(self.log_priors),
            jnp.asarray(self.log_theta),
            jnp.asarray(features, jnp.float32),
        )
        return self.labels[np.asarray(jnp.argmax(scores, axis=1))]


@jax.jit
def _nb_scores(log_priors, log_theta, x):
    return log_priors[None, :] + x @ log_theta.T


def train_naive_bayes(
    labels: np.ndarray, features: np.ndarray, smoothing: float = 1.0
) -> NaiveBayesModel:
    """Multinomial NB: theta_cf = (sum of f over class c + lambda) /
    (total over class c + lambda * F), matching MLlib semantics. Features
    must be non-negative."""
    labels = np.asarray(labels)
    features = np.asarray(features, np.float64)
    if np.any(features < 0):
        raise ValueError("multinomial naive Bayes requires non-negative features")
    classes = np.unique(labels)
    C, F = len(classes), features.shape[1]
    log_priors = np.zeros(C)
    log_theta = np.zeros((C, F))
    n = len(labels)
    for ci, c in enumerate(classes):
        mask = labels == c
        log_priors[ci] = np.log(mask.sum() / n)
        sums = features[mask].sum(axis=0)
        log_theta[ci] = np.log((sums + smoothing) / (sums.sum() + smoothing * F))
    return NaiveBayesModel(classes, log_priors, log_theta)


# ---------------------------------------------------------------------------
# Random forest (host-side; small tabular problems)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Node:
    feature: int = -1
    threshold: float = 0.0
    left: "_Node | None" = None
    right: "_Node | None" = None
    prediction: float = 0.0

    def predict(self, x: np.ndarray) -> float:
        node = self
        while node.feature >= 0:
            node = node.left if x[node.feature] <= node.threshold else node.right
        return node.prediction


def _gini(y: np.ndarray) -> float:
    if len(y) == 0:
        return 0.0
    _, counts = np.unique(y, return_counts=True)
    p = counts / len(y)
    return 1.0 - float(np.sum(p * p))


def _build_tree(
    X: np.ndarray,
    y: np.ndarray,
    rng: np.random.Generator,
    max_depth: int,
    n_sub_features: int,
) -> _Node:
    if max_depth == 0 or len(np.unique(y)) == 1 or len(y) < 4:
        values, counts = np.unique(y, return_counts=True)
        return _Node(prediction=float(values[np.argmax(counts)]))
    best = (None, None, np.inf)
    features = rng.choice(X.shape[1], size=min(n_sub_features, X.shape[1]), replace=False)
    for f in features:
        for t in np.unique(X[:, f])[:-1]:
            mask = X[:, f] <= t
            score = (_gini(y[mask]) * mask.sum() + _gini(y[~mask]) * (~mask).sum()) / len(y)
            if score < best[2]:
                best = (int(f), float(t), score)
    if best[0] is None:
        values, counts = np.unique(y, return_counts=True)
        return _Node(prediction=float(values[np.argmax(counts)]))
    f, t, _ = best
    mask = X[:, f] <= t
    return _Node(
        feature=f,
        threshold=t,
        left=_build_tree(X[mask], y[mask], rng, max_depth - 1, n_sub_features),
        right=_build_tree(X[~mask], y[~mask], rng, max_depth - 1, n_sub_features),
    )


@dataclasses.dataclass
class RandomForestModel:
    trees: list[_Node]

    def predict(self, x: np.ndarray) -> float:
        votes = [t.predict(np.asarray(x, np.float64)) for t in self.trees]
        values, counts = np.unique(votes, return_counts=True)
        return float(values[np.argmax(counts)])


def train_random_forest(
    labels: np.ndarray,
    features: np.ndarray,
    num_trees: int = 10,
    max_depth: int = 4,
    seed: int = 42,
) -> RandomForestModel:
    X = np.asarray(features, np.float64)
    y = np.asarray(labels)
    rng = np.random.default_rng(seed)
    n_sub = max(1, int(np.sqrt(X.shape[1])))
    trees = []
    for _ in range(num_trees):
        idx = rng.integers(0, len(y), size=len(y))  # bootstrap
        trees.append(_build_tree(X[idx], y[idx], rng, max_depth, n_sub))
    return RandomForestModel(trees)
