"""Attention kernels: fused single-chip attention (pallas) and ring
attention for sequence/context parallelism.

The reference has no sequence models (SURVEY.md section 5 — nearest analog
is the e2 MarkovChain), but long-context support is first-class in this
framework: a sequence encoder attached to any engine (see
``models/twotower``'s history encoder) must scale past single-chip memory.

Design:
  - ``ring_attention``: Q/K/V sharded over a named mesh axis (``sp``) along
    the sequence dimension. Each of the P ring steps computes one block of
    attention with a numerically-stable online softmax (flash-attention
    accumulation) and rotates the K/V shard to the next device with
    ``lax.ppermute`` — bandwidth rides ICI neighbor links, compute overlaps
    the permute under XLA's async scheduling. Supports causal masking with
    global position offsets.
  - ``fused_attention``: a pallas TPU kernel for the within-block attention
    (grid over batch x heads, K/V streamed through VMEM); falls back to the
    jnp reference path off-TPU. Used by ring_attention for its local block
    when running on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # stable home since jax 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

import inspect

# the replication/varying checker kwarg was renamed check_rep -> check_vma;
# pallas_call outputs carry no vma metadata, so the checker must be off for
# shard_map bodies that invoke pallas kernels
_NO_CHECK = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(shard_map).parameters
    else {"check_rep": False}
)


def _check_seq_divisible(L: int, axis: str, axis_size: int) -> None:
    if L % axis_size:
        raise ValueError(
            f"sequence length {L} not divisible by {axis}={axis_size}"
        )


# ---------------------------------------------------------------------------
# Reference (jnp) attention + online-softmax block update
# ---------------------------------------------------------------------------


def attention_reference(
    q: jnp.ndarray,  # [B, H, Lq, D]
    k: jnp.ndarray,  # [B, H, Lk, D]
    v: jnp.ndarray,  # [B, H, Lk, D]
    causal: bool = False,
    q_offset: int = 0,
    k_offset: int = 0,
) -> jnp.ndarray:
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None] + q_offset
        ki = jnp.arange(k.shape[2])[None, :] + k_offset
        scores = jnp.where(qi >= ki, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    # rows with no visible keys produce NaN from softmax(-inf row): zero them
    weights = jnp.nan_to_num(weights)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def _online_block(q, k, v, acc, row_max, row_sum, mask):
    """One flash-attention accumulation step.

    q [B,H,Lq,D]; k,v [B,H,Lk,D]; acc [B,H,Lq,D]; row_max/row_sum [B,H,Lq];
    mask [Lq, Lk] boolean (True = attend) or None.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    blk_max = jnp.max(scores, axis=-1)  # [B,H,Lq]
    new_max = jnp.maximum(row_max, blk_max)
    # guard fully-masked blocks: exp(-inf - -inf) -> use safe max
    safe_max = jnp.where(jnp.isneginf(new_max), 0.0, new_max)
    correction = jnp.exp(row_max - safe_max)
    correction = jnp.where(jnp.isneginf(row_max), 0.0, correction)
    p = jnp.exp(scores - safe_max[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    row_sum = row_sum * correction + jnp.sum(p, axis=-1)
    return acc, new_max, row_sum


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism over a mesh axis)
# ---------------------------------------------------------------------------


def ring_attention(
    q: jnp.ndarray,  # [B, H, L, D] — L is the GLOBAL sequence length
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
) -> jnp.ndarray:
    """Full attention over sequences sharded on ``axis``.

    Inputs/outputs are global arrays; under jit the sequence dimension is
    sharded over the axis and each device runs P ring steps, exchanging K/V
    shards with its neighbor. Requires L % axis_size == 0.
    """
    axis_size = mesh.shape[axis]
    L = q.shape[2]
    _check_seq_divisible(L, axis, axis_size)
    l_local = L // axis_size

    def local_fn(q_blk, k_blk, v_blk):
        # q_blk etc: [B, H, l_local, D] — this device's shard
        my_idx = lax.axis_index(axis)
        q_off = my_idx * l_local
        B, H, Lq, D = q_blk.shape
        # initial carries must share the input's varying-axes type under
        # shard_map's vma checking, so derive them from q_blk
        zero_rows = jnp.sum(q_blk.astype(jnp.float32) * 0.0, axis=-1)  # [B,H,Lq]
        acc0 = q_blk.astype(jnp.float32) * 0.0
        max0 = zero_rows - jnp.inf
        sum0 = zero_rows
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

        def step(i, carry):
            k_cur, v_cur, acc, row_max, row_sum = carry
            # the K/V block currently held came from device (my_idx - i)
            src = (my_idx - i) % axis_size
            k_off = src * l_local
            if causal:
                qi = jnp.arange(Lq)[:, None] + q_off
                ki = jnp.arange(Lq)[None, :] + k_off
                mask = qi >= ki
            else:
                mask = None
            acc, row_max, row_sum = _online_block(
                q_blk.astype(jnp.float32),
                k_cur.astype(jnp.float32),
                v_cur.astype(jnp.float32),
                acc,
                row_max,
                row_sum,
                mask,
            )
            k_nxt = lax.ppermute(k_cur, axis, perm)
            v_nxt = lax.ppermute(v_cur, axis, perm)
            return k_nxt, v_nxt, acc, row_max, row_sum

        _, _, acc, row_max, row_sum = lax.fori_loop(
            0, axis_size, step, (k_blk, v_blk, acc0, max0, sum0)
        )
        safe_sum = jnp.where(row_sum == 0.0, 1.0, row_sum)
        return (acc / safe_sum[..., None]).astype(q_blk.dtype)

    spec = P(None, None, axis, None)
    sharded = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return sharded(q, k, v)


def ring_attention_sharded(
    q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False
):
    """jit-wrapped ring attention with explicit input shardings."""
    sharding = NamedSharding(mesh, P(None, None, axis, None))
    fn = jax.jit(
        functools.partial(ring_attention, mesh=mesh, axis=axis, causal=causal),
        in_shardings=(sharding, sharding, sharding),
        out_shardings=sharding,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses attention (all-to-all sequence parallelism)
# ---------------------------------------------------------------------------


def ulysses_attention(
    q: jnp.ndarray,  # [B, H, L, D] — L is the GLOBAL sequence length
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme): inputs
    arrive sequence-sharded on ``axis``; one ``all_to_all`` re-shards them
    head-wise with the FULL sequence per device, attention runs locally with
    no inner communication, and a second ``all_to_all`` restores sequence
    sharding. Communication: 2 all-to-alls of activations total (vs. P-1
    K/V ``ppermute`` hops for ring attention) — the better schedule when
    heads are plentiful and the sequence shard still fits one device's
    memory as [H/P, L]. Requires H % axis_size == 0 and L % axis_size == 0.
    """
    axis_size = mesh.shape[axis]
    _, H, L, _ = q.shape
    _check_seq_divisible(L, axis, axis_size)
    if H % axis_size:
        raise ValueError(f"head count {H} not divisible by {axis}={axis_size}")

    def local_fn(q_blk, k_blk, v_blk):
        # [B, H, l_local, D] -> [B, H/P, L, D]: split heads, gather sequence
        def to_heads(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

        q_h, k_h, v_h = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        # full sequence is present locally: plain causal offsets (0, 0).
        # fused_attention keeps the local block flash-style (no dense
        # [L, L] score tensor on TPU) — the point of sequence parallelism
        out = fused_attention(q_h, k_h, v_h, causal=causal)
        return to_seq(out)

    spec = P(None, None, axis, None)
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_NO_CHECK,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Pallas fused attention (TPU single-chip hot path)
# ---------------------------------------------------------------------------


def _fused_attention_pallas(q, k, v, causal: bool, interpret: bool):
    from jax.experimental import pallas as pl

    B, H, Lq, D = q.shape
    Lk = k.shape[2]

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qb = q_ref[0]  # [Lq, D]
        kb = k_ref[0]
        vb = v_ref[0]
        scale = 1.0 / math.sqrt(D)
        # HIGHEST precision: the TPU default lowers f32 matmuls to bf16
        # passes (~7e-3 abs error vs float64 at these shapes); full f32
        # keeps the kernel within ~1e-6 of the dense reference
        scores = (
            jnp.dot(
                qb,
                kb.T,
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST,
            )
            * scale
        )
        if causal:
            qi = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
            ki = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
            scores = jnp.where(qi >= ki, scores, -jnp.inf)
        m = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - m)
        out = jnp.dot(
            p,
            vb,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )
        denom = jnp.sum(p, axis=-1, keepdims=True)
        o_ref[0] = (out / denom).astype(o_ref.dtype)

    grid = (B * H,)
    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, Lq, D), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Lq, D), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, Lq, D)


def fused_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    force_pallas: bool = False,
) -> jnp.ndarray:
    """Single-device attention. On TPU: pallas kernel (one (batch, head)
    block per grid step, softmax fused in VMEM). Elsewhere: the jnp
    reference path (``force_pallas`` runs the kernel in interpret mode for
    testing). Platform is sniffed via ``jax.default_backend()`` so the
    choice also works on tracers (e.g. inside shard_map)."""
    if jax.default_backend() == "tpu":
        return _fused_attention_pallas(q, k, v, causal, interpret=False)
    if force_pallas:
        return _fused_attention_pallas(q, k, v, causal, interpret=True)
    return attention_reference(q, k, v, causal=causal)
