"""Attention kernels: fused single-chip attention (pallas) and ring
attention for sequence/context parallelism.

The reference has no sequence models (SURVEY.md section 5 — nearest analog
is the e2 MarkovChain), but long-context support is first-class in this
framework: a sequence encoder attached to any engine (see
``models/twotower``'s history encoder) must scale past single-chip memory.

Design:
  - ``ring_attention``: Q/K/V sharded over a named mesh axis (``sp``) along
    the sequence dimension. Each of the P ring steps computes one block of
    attention with a numerically-stable online softmax (flash-attention
    accumulation) and rotates the K/V shard to the next device with
    ``lax.ppermute`` — bandwidth rides ICI neighbor links, compute overlaps
    the permute under XLA's async scheduling. Supports causal masking with
    global position offsets.
  - ``fused_attention``: a pallas TPU kernel for the within-block attention
    (grid over batch x heads, K/V streamed through VMEM); falls back to the
    jnp reference path off-TPU. Used by ring_attention for its local block
    when running on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # stable home since jax 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

import inspect

# the replication/varying checker kwarg was renamed check_rep -> check_vma;
# pallas_call outputs carry no vma metadata, so the checker must be off for
# shard_map bodies that invoke pallas kernels
_NO_CHECK = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(shard_map).parameters
    else {"check_rep": False}
)


def _check_seq_divisible(L: int, axis: str, axis_size: int) -> None:
    if L % axis_size:
        raise ValueError(
            f"sequence length {L} not divisible by {axis}={axis_size}"
        )


# ---------------------------------------------------------------------------
# Reference (jnp) attention + online-softmax block update
# ---------------------------------------------------------------------------


def attention_reference(
    q: jnp.ndarray,  # [B, H, Lq, D]
    k: jnp.ndarray,  # [B, H, Lk, D]
    v: jnp.ndarray,  # [B, H, Lk, D]
    causal: bool = False,
    q_offset: int = 0,
    k_offset: int = 0,
) -> jnp.ndarray:
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None] + q_offset
        ki = jnp.arange(k.shape[2])[None, :] + k_offset
        scores = jnp.where(qi >= ki, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    # rows with no visible keys produce NaN from softmax(-inf row): zero them
    weights = jnp.nan_to_num(weights)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def _online_block(q, k, v, acc, row_max, row_sum, mask):
    """One flash-attention accumulation step.

    q [B,H,Lq,D]; k,v [B,H,Lk,D]; acc [B,H,Lq,D]; row_max/row_sum [B,H,Lq];
    mask [Lq, Lk] boolean (True = attend) or None.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    blk_max = jnp.max(scores, axis=-1)  # [B,H,Lq]
    new_max = jnp.maximum(row_max, blk_max)
    # guard fully-masked blocks: exp(-inf - -inf) -> use safe max
    safe_max = jnp.where(jnp.isneginf(new_max), 0.0, new_max)
    correction = jnp.exp(row_max - safe_max)
    correction = jnp.where(jnp.isneginf(row_max), 0.0, correction)
    p = jnp.exp(scores - safe_max[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    row_sum = row_sum * correction + jnp.sum(p, axis=-1)
    return acc, new_max, row_sum


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism over a mesh axis)
# ---------------------------------------------------------------------------


def ring_attention(
    q: jnp.ndarray,  # [B, H, L, D] — L is the GLOBAL sequence length
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    batch_axis: str | None = None,
) -> jnp.ndarray:
    """Full attention over sequences sharded on ``axis``.

    Inputs/outputs are global arrays; under jit the sequence dimension is
    sharded over the axis and each device runs P ring steps, exchanging K/V
    shards with its neighbor. Requires L % axis_size == 0.

    ``batch_axis`` composes sequence parallelism with data parallelism:
    the batch dimension shards over that mesh axis (dp x sp over one 2-D
    mesh), so a dp-sharded caller (e.g. a sharded train step) does not
    force GSPMD to all-gather the batch around the shard_map boundary.
    """
    axis_size = mesh.shape[axis]
    L = q.shape[2]
    _check_seq_divisible(L, axis, axis_size)
    l_local = L // axis_size

    def local_fn(q_blk, k_blk, v_blk):
        # q_blk etc: [B, H, l_local, D] — this device's shard
        my_idx = lax.axis_index(axis)
        q_off = my_idx * l_local
        B, H, Lq, D = q_blk.shape
        # initial carries must share the input's varying-axes type under
        # shard_map's vma checking, so derive them from q_blk
        zero_rows = jnp.sum(q_blk.astype(jnp.float32) * 0.0, axis=-1)  # [B,H,Lq]
        acc0 = q_blk.astype(jnp.float32) * 0.0
        max0 = zero_rows - jnp.inf
        sum0 = zero_rows
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

        def step(i, carry):
            k_cur, v_cur, acc, row_max, row_sum = carry
            # the K/V block currently held came from device (my_idx - i)
            src = (my_idx - i) % axis_size
            k_off = src * l_local
            if causal:
                qi = jnp.arange(Lq)[:, None] + q_off
                ki = jnp.arange(Lq)[None, :] + k_off
                mask = qi >= ki
            else:
                mask = None
            acc, row_max, row_sum = _online_block(
                q_blk.astype(jnp.float32),
                k_cur.astype(jnp.float32),
                v_cur.astype(jnp.float32),
                acc,
                row_max,
                row_sum,
                mask,
            )
            k_nxt = lax.ppermute(k_cur, axis, perm)
            v_nxt = lax.ppermute(v_cur, axis, perm)
            return k_nxt, v_nxt, acc, row_max, row_sum

        _, _, acc, row_max, row_sum = lax.fori_loop(
            0, axis_size, step, (k_blk, v_blk, acc0, max0, sum0)
        )
        safe_sum = jnp.where(row_sum == 0.0, 1.0, row_sum)
        return (acc / safe_sum[..., None]).astype(q_blk.dtype)

    spec = P(batch_axis, None, axis, None)
    sharded = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return sharded(q, k, v)


def ring_attention_sharded(
    q,
    k,
    v,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    batch_axis: str | None = None,
):
    """jit-wrapped ring attention with explicit input shardings."""
    sharding = NamedSharding(mesh, P(batch_axis, None, axis, None))
    fn = jax.jit(
        functools.partial(
            ring_attention, mesh=mesh, axis=axis, causal=causal,
            batch_axis=batch_axis,
        ),
        in_shardings=(sharding, sharding, sharding),
        out_shardings=sharding,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses attention (all-to-all sequence parallelism)
# ---------------------------------------------------------------------------


def ulysses_attention(
    q: jnp.ndarray,  # [B, H, L, D] — L is the GLOBAL sequence length
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    batch_axis: str | None = None,
) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme): inputs
    arrive sequence-sharded on ``axis``; one ``all_to_all`` re-shards them
    head-wise with the FULL sequence per device, attention runs locally with
    no inner communication, and a second ``all_to_all`` restores sequence
    sharding. Communication: 2 all-to-alls of activations total (vs. P-1
    K/V ``ppermute`` hops for ring attention) — the better schedule when
    heads are plentiful and the sequence shard still fits one device's
    memory as [H/P, L]. Requires H % axis_size == 0 and L % axis_size == 0.
    """
    axis_size = mesh.shape[axis]
    _, H, L, _ = q.shape
    _check_seq_divisible(L, axis, axis_size)
    if H % axis_size:
        raise ValueError(f"head count {H} not divisible by {axis}={axis_size}")

    def local_fn(q_blk, k_blk, v_blk):
        # [B, H, l_local, D] -> [B, H/P, L, D]: split heads, gather sequence
        def to_heads(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

        q_h, k_h, v_h = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        # full sequence is present locally: plain causal offsets (0, 0).
        # fused_attention keeps the local block flash-style (no dense
        # [L, L] score tensor on TPU) — the point of sequence parallelism
        out = fused_attention(q_h, k_h, v_h, causal=causal)
        return to_seq(out)

    # batch_axis: dp x sp composition — see ring_attention
    spec = P(batch_axis, None, axis, None)
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_NO_CHECK,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Pallas fused attention (TPU single-chip hot path)
# ---------------------------------------------------------------------------


def _best_block(L: int) -> int:
    """Largest of 1024/512/256 dividing L. A round-4 sweep on a v5e at
    B4 H8 D64 causal measured (block_q, block_k) = (1024, 1024) fastest at
    every L it divides: L=2048 0.41ms vs 0.53ms for 512x512 (and 0.58ms
    for the XLA dense reference); L=4096 1.74ms vs 2.75ms (XLA reference
    9.04ms — the [L, L] score materialization falls off a cliff). Bigger
    tiles amortize the online-softmax rescale and keep the MXU on longer
    contractions; [1024, 1024] f32 scores + accumulators still fit VMEM."""
    for b in (1024, 512, 256):
        if L % b == 0:
            return b
    return L


def _flash_attention_pallas(
    q, k, v, causal: bool, interpret: bool, block_q: int = 1024, block_k: int = 1024
):
    """Tiled flash-attention pallas kernel: grid (B*H, Lq/bq, Lk/bk), online
    softmax carried across the (sequential, innermost) K-block grid axis in
    VMEM scratch. The single-block kernel below materializes the full
    [Lq, Lk] score matrix in VMEM, which blows the ~16MB scoped-VMEM limit
    at L=2048 (first observed on real hardware in the round-3 bench — the
    kernel had only ever run in interpret mode before); this one peaks at
    [bq, bk] scores + [bq, D] accumulators regardless of L."""
    import math as _math

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = min(block_q, Lq), min(block_k, Lk)
    assert Lq % bq == 0 and Lk % bk == 0, "flash path requires divisible blocks"
    nq, nk = Lq // bq, Lk // bk
    scale = 1.0 / _math.sqrt(D)

    def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        # program ids hoisted out of the pl.when bodies: the interpret-mode
        # lowering can't evaluate program_id inside a nested cond
        qi_blk = pl.program_id(1)
        kj = pl.program_id(2)

        @pl.when(kj == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)

        def compute():
            # bf16 multiplies, f32 accumulation: the MXU's native contract
            # and the flash-attention standard — HIGHEST (3-pass f32)
            # measured ~6x slower on a v5e for ~1e-2 output delta that the
            # softmax re-normalization mostly washes out anyway
            s = (
                jnp.dot(
                    q_ref[0].astype(jnp.bfloat16),
                    k_ref[0].astype(jnp.bfloat16).T,
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            if causal:
                qi = qi_blk * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                ki = kj * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
                s = jnp.where(qi >= ki, s, -jnp.inf)
            m_prev = m_ref[...]  # [bq, 1]
            m_blk = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_blk)
            safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
            corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - safe))
            p = jnp.exp(s - safe)
            p = jnp.where(jnp.isneginf(s), 0.0, p)
            acc_ref[...] = acc_ref[...] * corr + jnp.dot(
                p.astype(jnp.bfloat16),
                v_ref[0].astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1, keepdims=True)
            m_ref[...] = m_new

        if causal:
            # skip K blocks lying entirely above the diagonal: they are
            # fully masked and would only burn MXU cycles (~2x at nq == nk)
            @pl.when(kj * bk <= (qi_blk + 1) * bq - 1)
            def _():
                compute()
        else:
            compute()

        @pl.when(kj == nk - 1)
        def _finish():
            denom = l_ref[...]
            denom = jnp.where(denom == 0.0, 1.0, denom)
            o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)

    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, Lq, D)


def _fused_attention_pallas(q, k, v, causal: bool, interpret: bool):
    from jax.experimental import pallas as pl

    B, H, Lq, D = q.shape
    Lk = k.shape[2]

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qb = q_ref[0]  # [Lq, D]
        kb = k_ref[0]
        vb = v_ref[0]
        scale = 1.0 / math.sqrt(D)
        # bf16 multiply / f32 accumulate — see _flash_attention_pallas
        scores = (
            jnp.dot(
                qb.astype(jnp.bfloat16),
                kb.astype(jnp.bfloat16).T,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            qi = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
            ki = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
            scores = jnp.where(qi >= ki, scores, -jnp.inf)
        m = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - m)
        out = jnp.dot(
            p.astype(jnp.bfloat16),
            vb.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        denom = jnp.sum(p, axis=-1, keepdims=True)
        o_ref[0] = (out / denom).astype(o_ref.dtype)

    grid = (B * H,)
    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, Lq, D), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Lq, D), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, Lq, D)


def fused_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    force_pallas: bool = False,
) -> jnp.ndarray:
    """Single-device attention. On TPU: pallas kernel — the single-block
    variant when the whole [Lq, Lk] score tile fits VMEM comfortably, the
    tiled flash variant for long sequences. Elsewhere: the jnp reference
    path (``force_pallas`` runs the kernels in interpret mode for testing).
    Platform is sniffed via ``jax.default_backend()`` so the choice also
    works on tracers (e.g. inside shard_map)."""
    Lq, Lk = q.shape[2], k.shape[2]
    # remote-attach plugins (axon) report backend "tpu" in practice, but
    # match both spellings so a plugin that surfaces its own name can never
    # silently route "pallas" benchmarks to the jnp reference
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu or force_pallas:
        interpret = not on_tpu
        # score tile VMEM budget: single-block kernel holds [Lq, Lk] f32
        # (strict <: a 4MiB tile — L=1024 square — already takes the flash
        # path, which the interpret-mode routing test pins)
        if Lq * Lk * 4 < 4 * 1024 * 1024:
            return _fused_attention_pallas(q, k, v, causal, interpret=interpret)
        if Lq % 256 == 0 and Lk % 256 == 0:
            # block sizes tuned per-shape (see _best_block): the largest
            # dividing tile wins on the MXU at every measured length
            return _flash_attention_pallas(
                q, k, v, causal, interpret=interpret,
                block_q=_best_block(Lq), block_k=_best_block(Lk),
            )
        if on_tpu:
            # long ragged sequence: fall back to the jnp path rather than
            # risk the single-block kernel's VMEM limit
            return attention_reference(q, k, v, causal=causal)
        return _fused_attention_pallas(q, k, v, causal, interpret=True)
    return attention_reference(q, k, v, causal=causal)
