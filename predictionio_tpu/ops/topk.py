"""Shared fused score->mask->top-k serving kernels.

Every serving engine used to run its own ending: the recommendation
template already kept score+select fused on device (``ops/als.ServingIndex``,
the ALX recipe — batched matmul feeding ``lax.top_k``, one packed [B,2,k]
int32 fetch), while twotower / similarproduct / ecommerce / recommendeduser
fetched the FULL score vector to host and argsorted there. On a tunneled
chip that is O(batch * corpus) floats over the wire per batch; through this
module it becomes O(batch * k) for everyone.

Design (mirrors ops/als):
  - score + mask + select compile into ONE jitted program per
    (batch-bucket, k-bucket) shape; the resident factor table never moves.
  - results come back as a single packed int32 fetch: row 0 carries the
    float32 score bits via ``bitcast_convert_type`` (packing indices as
    floats would flush small indices to denormal zero), row 1 the indices.
  - per-batch host buffers (query vectors, gathered indices, masks) are
    DONATED to the kernel (``donate_argnums``): XLA may reuse their device
    allocation for the output instead of holding both live. The resident
    table argument is never donated. Donation is a no-op on the CPU
    backend; the warning it would log is filtered below.
  - ``ScratchBuffers`` gives the dispatch path preallocated, reusable host
    staging buffers (thread-local: the micro-batcher's dispatch thread and
    the shadow/stable-retry threads each get their own pool), so batch
    assembly writes queries straight into a recycled numpy buffer instead
    of allocating per window. Reuse is only sound because every staging
    upload goes through ``ops.als.upload`` (re-exported here), which
    COPIES: ``jnp.asarray`` on the CPU backend aliases host numpy memory,
    and an aliased buffer overwritten for batch N+1 while batch N's
    kernel is still in flight serves batch N the wrong queries.
  - ``host_top_k`` is the sanctioned HOST ending for score vectors that
    are host-born in the first place (popularity counts, cooccurrence
    maps). It lives here so the ``serving-host-roundtrip`` lint rule can
    hold engines to "no argsort outside the fused helper".
"""

from __future__ import annotations

import functools
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from predictionio_tpu.ops.als import next_pow2, upload

__all__ = [
    "dot_top_k_async",
    "gather_sum_top_k_async",
    "fused_top_k_async",
    "fetch_topk",
    "host_top_k",
    "warmup_pow2_buckets",
    "pack_batch",
    "scratch",
    "upload",
    "ScratchBuffers",
    "next_pow2",
]

# donation is unsupported on the CPU backend; jax warns once per compiled
# donating program. The fallback (plain copy) is exactly the pre-donation
# behavior, so the warning is noise on CPU dev boxes — filtered narrowly
# by message for server/CLI runs. Under pytest this import-time filter is
# overridden by the test config; pyproject.toml carries the matching
# filterwarnings entry for CI.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


def pack_batch(scores: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """[B,k] scores + [B,k] indices -> packed [B,2,k] int32 (score bits in
    row 0 — same wire idiom as ops/als). Public so engines composing their
    own device program (e.g. the two-tower forward) can end it on the
    same one-fetch wire format ``fetch_topk`` decodes."""
    return jnp.stack([lax.bitcast_convert_type(scores, jnp.int32), idx], axis=1)


_pack_batch = pack_batch  # internal alias


@functools.partial(
    jax.jit, static_argnames=("k",), donate_argnums=(1, 2)
)
def _dot_top_k(table, vecs, mask, k: int):
    """scores = vecs @ table.T, masked, top-k. table [n,f] resident;
    vecs [B,f] and mask [B,n] are per-batch uploads (donated)."""
    scores = vecs @ table.T  # [B, n] on the MXU
    scores = jnp.where(mask, scores, -jnp.inf)
    s, i = lax.top_k(scores, k)
    return _pack_batch(s, i)


@functools.partial(
    jax.jit, static_argnames=("k",), donate_argnums=(1,)
)
def _dot_top_k_unmasked(table, vecs, k: int):
    scores = vecs @ table.T
    s, i = lax.top_k(scores, k)
    return _pack_batch(s, i)


@functools.partial(
    jax.jit, static_argnames=("k",), donate_argnums=(1, 2, 3)
)
def _dot_top_k_weighted(table, vecs, mask, weights, k: int):
    """The adjust-score variant: a per-item weight vector multiplies the
    scores before selection (weights ride up per call, donated)."""
    scores = (vecs @ table.T) * weights[None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    s, i = lax.top_k(scores, k)
    return pack_batch(s, i)


@functools.partial(
    jax.jit, static_argnames=("k",), donate_argnums=(1, 2, 3)
)
def _gather_sum_top_k(table, qidx, qweight, mask, k: int):
    """The summed-similarity pattern (similarproduct / recommendeduser):
    gather the query rows, matmul against the whole table, sum over the
    query axis, mask, select. table [n,f]; qidx [B,Q] int32 (pad rows point
    at row 0 and are zero-weighted); qweight [B,Q] float32; mask [B,n]."""
    q = table[qidx] * qweight[..., None]  # [B, Q, f]
    scores = jnp.einsum("nf,bqf->bn", table, q)
    scores = jnp.where(mask, scores, -jnp.inf)
    s, i = lax.top_k(scores, k)
    return pack_batch(s, i)


@functools.partial(
    jax.jit, static_argnames=("k",), donate_argnums=(1, 2, 3, 4)
)
def _gather_sum_top_k_weighted(table, qidx, qweight, mask, weights, k: int):
    q = table[qidx] * qweight[..., None]
    scores = jnp.einsum("nf,bqf->bn", table, q) * weights[None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    s, i = lax.top_k(scores, k)
    return pack_batch(s, i)


@functools.partial(
    jax.jit, static_argnames=("k",), donate_argnums=(0, 1)
)
def _mask_top_k(scores, mask, k: int):
    scores = jnp.where(mask, scores, -jnp.inf)
    s, i = lax.top_k(scores, k)
    return _pack_batch(s, i)


def dot_top_k_async(table, vecs, mask, k: int, weights=None):
    """Dispatch (no fetch) the fused matmul+mask+top-k: ``table`` [n,f]
    device-resident, ``vecs`` [B,f], ``mask`` [B,n] bool or None,
    ``weights`` an optional [n] per-item score multiplier. Returns the
    packed [B,2,k] device handle; decode with :func:`fetch_topk`."""
    vecs_d = upload(vecs, np.float32)
    if weights is not None:
        m = (
            upload(mask)
            if mask is not None
            else jnp.ones((vecs_d.shape[0], table.shape[0]), bool)
        )
        return _dot_top_k_weighted(
            table, vecs_d, m, upload(weights, np.float32), k
        )
    if mask is None:
        return _dot_top_k_unmasked(table, vecs_d, k)
    return _dot_top_k(table, vecs_d, upload(mask), k)


def gather_sum_top_k_async(table, qidx, qweight, mask, k: int, weights=None):
    """Dispatch the gather->sum->mask->top-k kernel; see
    :func:`_gather_sum_top_k` for shapes. Returns the packed handle."""
    qidx_d = upload(qidx, np.int32)
    qw_d = upload(qweight, np.float32)
    mask_d = upload(mask)
    if weights is not None:
        return _gather_sum_top_k_weighted(
            table, qidx_d, qw_d, mask_d, upload(weights, np.float32), k
        )
    return _gather_sum_top_k(table, qidx_d, qw_d, mask_d, k)


def fused_top_k_async(scores, mask, k: int):
    """Mask + top-k over an already-computed device score matrix [B,n]
    (both donated — the scores buffer is consumed by the selection)."""
    return _mask_top_k(scores, upload(mask), k)


def fetch_topk(handle) -> tuple[np.ndarray, np.ndarray]:
    """The ONE sanctioned device->host fetch on the serving path: a packed
    [B,2,k] (or [2,k]) int32 result — O(batch*k), never O(batch*corpus).
    Returns ([B,k] float32 scores, [B,k] int32 indices)."""
    from predictionio_tpu.ops.als import ServingIndex

    # pio-lint: disable=serving-host-roundtrip -- the ONE sanctioned fetch: O(batch*k) packed result, accounted by the request waterfall
    packed = np.asarray(handle)
    if packed.ndim == 2:  # single-query [2,k]
        packed = packed[None]
    # ops/als owns the wire format; this is the one decode of it
    return ServingIndex.unpack_batch(packed)


def warmup_pow2_buckets(max_batch: int, dispatch) -> None:
    """Shared engine warmup: pre-compile one fused program per pow2 batch
    bucket by calling ``dispatch(b)`` for b = 1, 2, ..., next_pow2(max_batch)
    and blocking on every returned handle, so the first burst after
    deploy/reload pays no XLA compiles on the common shapes. ``dispatch``
    is the engine's per-bucket kernel call (dot / gather-sum / tower)."""
    import jax

    handles = []
    b = 1
    top = next_pow2(max_batch)
    while b <= top:
        handles.append(dispatch(b))
        b *= 2
    jax.block_until_ready(handles)


def host_top_k(
    scores: np.ndarray, mask: np.ndarray | None, k: int
) -> tuple[np.ndarray, np.ndarray]:
    """Host ending for host-born score vectors (popularity counts,
    cooccurrence maps — nothing device-resident to fuse with). Masked
    entries and -inf scores never surface. Returns (scores_k, idx_k)
    sorted descending; may return fewer than k when the finite pool is
    smaller."""
    scores = np.asarray(scores, np.float64)
    if mask is not None:
        scores = np.where(mask, scores, -np.inf)
    k = min(int(k), scores.shape[0])
    if k <= 0:
        return np.empty(0), np.empty(0, np.int64)
    # pio-lint: disable=serving-host-roundtrip -- host-born scores (popularity/cooccurrence): this IS the sanctioned host ending, no device round-trip
    idx = np.argpartition(-scores, k - 1)[:k]
    # pio-lint: disable=serving-host-roundtrip -- host-born scores: same sanctioned host ending
    idx = idx[np.argsort(-scores[idx])]
    finite = np.isfinite(scores[idx])
    idx = idx[finite]
    return scores[idx], idx


class ScratchBuffers:
    """Reusable host staging buffers for batch assembly.

    ``get(name, shape, dtype)`` returns a preallocated array, growing a
    named slot geometrically (pow2 per axis) so steady-state serving does
    zero per-batch allocation; the caller owns the buffer until its next
    ``get`` of the same name. ``zeros``/``full`` variants re-fill in place.
    NOT thread-safe by design — use :func:`scratch` for the thread-local
    pool.
    """

    def __init__(self):
        self._bufs: dict[str, np.ndarray] = {}

    def get(self, name: str, shape: tuple[int, ...], dtype) -> np.ndarray:
        dtype = np.dtype(dtype)
        buf = self._bufs.get(name)
        if buf is None or buf.dtype != dtype or any(
            have < want for have, want in zip(buf.shape, shape)
        ) or buf.ndim != len(shape):
            alloc = tuple(max(1, next_pow2(s)) for s in shape)
            if buf is not None and buf.dtype == dtype and buf.ndim == len(shape):
                alloc = tuple(
                    max(a, have) for a, have in zip(alloc, buf.shape)
                )
            buf = np.empty(alloc, dtype)
            self._bufs[name] = buf
        view = buf[tuple(slice(0, s) for s in shape)]
        return view

    def zeros(self, name: str, shape: tuple[int, ...], dtype) -> np.ndarray:
        view = self.get(name, shape, dtype)
        view[...] = 0
        return view

    def full(self, name: str, shape: tuple[int, ...], dtype, value) -> np.ndarray:
        view = self.get(name, shape, dtype)
        view[...] = value
        return view


_SCRATCH = threading.local()


def scratch() -> ScratchBuffers:
    """The calling thread's scratch pool (dispatch thread, shadow thread
    and stable-retry fetch threads must not share staging buffers)."""
    pool = getattr(_SCRATCH, "pool", None)
    if pool is None:
        pool = _SCRATCH.pool = ScratchBuffers()
    return pool
