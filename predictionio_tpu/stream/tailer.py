"""Event tailer: bounded micro-batch drains behind resilience policies.

Wraps an ``LEvents`` DAO's ``find_after`` tail read (the ordering
contract of ``data/storage/base.event_seq_key``) with the PR-2 policy
vocabulary: transient storage errors are retried with backoff, persistent
failure opens a circuit breaker (``CircuitOpenError`` surfaces to the
pipeline, which pauses tailing until the breaker's recovery window), and
every drain runs under its own deadline so a wedged backend cannot stall
the loop forever. Batches are bounded by ``batch_limit`` — backpressure
is structural: the tailer never materializes more than one batch.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Any

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import event_seq_key
from predictionio_tpu.obs.tracing import get_tracer
from predictionio_tpu.resilience import (
    CircuitBreaker,
    Deadline,
    ResiliencePolicy,
    RetryPolicy,
)
from predictionio_tpu.stream.cursor import Position

_UTC = _dt.timezone.utc


@dataclasses.dataclass
class DrainResult:
    """One micro-batch: the events, the cursor position after them, and
    whether the store likely has more (a full batch came back)."""

    events: list[Event]
    position: Position | None  # unchanged when the drain was empty
    more: bool


def default_tail_policy(
    breaker_threshold: int = 5, breaker_recovery_s: float = 5.0
) -> ResiliencePolicy:
    return ResiliencePolicy(
        retry=RetryPolicy(max_attempts=3, backoff_base_s=0.05),
        breaker=CircuitBreaker(
            name="stream-tail",
            failure_threshold=breaker_threshold,
            recovery_timeout_s=breaker_recovery_s,
        ),
    )


class EventTailer:
    """Drains new events for one (app, channel) in bounded batches."""

    def __init__(
        self,
        levents: Any,
        app_id: int,
        channel_id: int | None = None,
        *,
        batch_limit: int = 500,
        drain_timeout_s: float = 10.0,
        lag_probe_limit: int = 1000,
        safety_lag_s: float = 0.0,
        policy: ResiliencePolicy | None = None,
        tracer=None,
    ):
        if batch_limit <= 0:
            raise ValueError(f"batch_limit must be positive, got {batch_limit}")
        self.levents = levents
        self.app_id = app_id
        self.channel_id = channel_id
        self.batch_limit = batch_limit
        self.drain_timeout_s = drain_timeout_s
        self.lag_probe_limit = lag_probe_limit
        # Watermark against the concurrent-commit race: creation_time is
        # stamped at Event CONSTRUCTION, so a slow commit can land behind
        # an already-advanced cursor and be skipped. With a safety lag,
        # the drain never advances past (now - safety_lag_s) — any insert
        # whose construct->commit latency is under the lag is safe.
        # 0 (default) trusts commit latency ~0 (single-writer embedded
        # stores, tests); `pio stream` defaults it on (docs/streaming.md).
        self.safety_lag_s = max(0.0, safety_lag_s)
        self.policy = policy or default_tail_policy()
        self.tracer = tracer or get_tracer()

    def _read(self, position: Position | None, limit: int) -> list[Event]:
        return self.levents.find_after(
            self.app_id,
            self.channel_id,
            cursor=position,
            limit=limit,
        )

    def drain(self, position: Position | None) -> DrainResult:
        """One bounded tail read strictly past ``position``. Retries ride
        the policy; a tripped breaker raises ``CircuitOpenError`` here and
        the caller pauses."""
        with self.tracer.span(
            "stream.drain", kind="stream", app_id=self.app_id
        ) as sp:
            events = self.policy.call(
                self._read,
                position,
                self.batch_limit,
                deadline=Deadline.after(self.drain_timeout_s),
            )
            full = len(events) >= self.batch_limit
            if self.safety_lag_s > 0 and events:
                cutoff = _dt.datetime.now(tz=_UTC) - _dt.timedelta(
                    seconds=self.safety_lag_s
                )
                kept = len(events)
                while kept and events[kept - 1].creation_time > cutoff:
                    kept -= 1
                if kept < len(events):
                    # the tail is inside the watermark window: leave it
                    # for the next cycle (more=False — waiting, not behind)
                    events = events[:kept]
                    full = False
            sp.tags["events"] = len(events)
            # row/entity cardinality of the drain: how many distinct
            # entities this batch will touch downstream (the fold-in's
            # solve size is proportional to it, not to the event count)
            sp.tags["entities"] = len(
                {e.entity_id for e in events}
                | {
                    e.target_entity_id
                    for e in events
                    if e.target_entity_id is not None
                }
            )
        if not events:
            return DrainResult([], position, False)
        return DrainResult(events, event_seq_key(events[-1]), full)

    def lag(
        self, position: Position | None, assume_backlog: bool = False
    ) -> tuple[int, float]:
        """(events behind, seconds behind): a bounded probe past the
        cursor, under the same policy + deadline as ``drain`` (a wedged
        backend must open the breaker here too, not hang the loop). The
        event count saturates at ``lag_probe_limit``; seconds = age of
        the OLDEST unprocessed event (0 when caught up).

        ``assume_backlog=True`` (the caller just hit its drain budget
        with a full batch still pending) reads ONE row for the age and
        reports the saturated count — re-fetching up to the probe limit
        would double the read I/O on exactly the rows the next cycle's
        drain is about to read."""
        limit = 1 if assume_backlog else self.lag_probe_limit
        probe = self.policy.call(
            self._read,
            position,
            limit,
            deadline=Deadline.after(self.drain_timeout_s),
        )
        if not probe:
            return 0, 0.0
        oldest = probe[0].creation_time
        now = _dt.datetime.now(tz=_UTC)
        n = self.lag_probe_limit if assume_backlog else len(probe)
        return n, max(0.0, (now - oldest).total_seconds())

    def head_position(self) -> Position | None:
        """The current end of the store in tail order — what a fresh
        cursor is seeded with so only NEW events fold in. One
        ``seq_head`` call (indexed DESC read on sql/sqlite, one scan on
        the others), policy-wrapped."""
        return self.policy.call(
            self.levents.seq_head,
            self.app_id,
            self.channel_id,
            deadline=Deadline.after(self.drain_timeout_s),
        )

    def snapshot(self) -> dict[str, Any]:
        return {
            "appId": self.app_id,
            "channelId": self.channel_id,
            "batchLimit": self.batch_limit,
            "policy": self.policy.snapshot(),
        }


__all__ = ["DrainResult", "EventTailer", "default_tail_policy"]
