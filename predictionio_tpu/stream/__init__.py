"""Speed layer: streaming incremental training from the event store.

The paper frames PredictionIO as a Lambda architecture; until this package
the reproduction only had the batch half (events accumulate, models change
when a full ``pio train`` runs). The speed layer closes the loop:

- :mod:`.cursor` — durable per-app cursors into the event store (atomic
  tmp+rename state files; resume-after-crash; the bookkeeping behind
  exactly-once *publish* on top of at-least-once event reads);
- :mod:`.tailer` — drains new events in bounded micro-batches behind the
  PR-2 resilience policies (retry transient storage errors, breaker
  pauses tailing, deadline per drain);
- :mod:`.trainers` — the :class:`~predictionio_tpu.stream.trainers.
  IncrementalTrainer` protocol plus fold-in ALS (batched SPD solves via
  ``ops/spd_solve``), streaming naive-Bayes count updates, and
  incremental cooccurrence counts — each with a rolling held-out drift
  guard;
- :mod:`.pipeline` — the ``pio stream`` driver: drain -> fold-in ->
  snapshot -> publish a *candidate* to the model registry, where the
  existing bake gates and candidate breaker decide promote/rollback
  (docs/streaming.md, docs/DECISIONS.md).
"""

from predictionio_tpu.stream.cursor import CursorStore, StreamCursor, span_id_of
from predictionio_tpu.stream.pipeline import (
    StreamConfig,
    StreamInstruments,
    StreamPipeline,
    serve_metrics,
    trainer_for_models,
)
from predictionio_tpu.stream.tailer import DrainResult, EventTailer
from predictionio_tpu.stream.trainers import (
    DriftReport,
    FoldInALSTrainer,
    IncrementalTrainer,
    RollingHoldout,
    StreamingCooccurrenceTrainer,
    StreamingNaiveBayesTrainer,
)

__all__ = [
    "CursorStore",
    "DrainResult",
    "DriftReport",
    "EventTailer",
    "FoldInALSTrainer",
    "IncrementalTrainer",
    "RollingHoldout",
    "StreamConfig",
    "StreamInstruments",
    "StreamPipeline",
    "StreamCursor",
    "StreamingCooccurrenceTrainer",
    "StreamingNaiveBayesTrainer",
    "serve_metrics",
    "span_id_of",
    "trainer_for_models",
]
