"""Durable stream cursors: checkpointed positions into the event store.

One cursor file per tailed (app, channel), JSON under a base directory
(default ``$PIO_STREAM_DIR``, else ``stream/`` next to the registry under
``$PIO_FS_BASEDIR``). Every write is atomic (tmp file + ``os.replace`` in
the same directory, fsync'd) so a crashed pipeline can never leave a
half-written cursor — restart resumes from the last complete checkpoint.

The position is the event store's documented ordering contract
(:func:`predictionio_tpu.data.storage.base.event_seq_key`): a
``(creation_time_micros, event_id)`` pair, exclusive. Reads are
at-least-once by design (a crash between fold-in and checkpoint re-reads
the last drain); exactly-once applies to *publish* — the pipeline derives
a deterministic span id from the cursor interval a publish covers and the
registry is consulted for that span id before publishing, so a replayed
interval can never produce a second candidate (docs/streaming.md).

Stdlib-only; no jax/numpy.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import os
import tempfile
import threading
from typing import Any

logger = logging.getLogger(__name__)

Position = tuple[int, str]  # (creation_time micros, event_id), exclusive

_UTC = _dt.timezone.utc


def _now_iso() -> str:
    return _dt.datetime.now(tz=_UTC).isoformat()


def default_stream_dir() -> str:
    """Resolution order: ``PIO_STREAM_DIR``, else ``stream/`` under
    ``PIO_FS_BASEDIR`` (or its ``~/.pio_store`` default)."""
    explicit = os.environ.get("PIO_STREAM_DIR")
    if explicit:
        return explicit
    base = os.environ.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
    )
    return os.path.join(base, "stream")


def position_str(position: Position | None) -> str:
    return "start" if position is None else f"{position[0]}:{position[1]}"


def span_id_of(frm: Position | None, to: Position) -> str:
    """Deterministic identity of one publish's cursor interval. Replaying
    the same interval (at-least-once reads after a crash) derives the same
    span id, which is how the registry-side dedup recognizes an already
    published candidate."""
    return f"{position_str(frm)}..{position_str(to)}"


@dataclasses.dataclass
class StreamCursor:
    """Checkpointed tail state for one (app, channel)."""

    app_id: int
    channel_id: int | None = None
    # [creation_time_micros, event_id]; None = start of the store
    position: list | None = None
    # position covered by the last PUBLISH (or the initial seed). On
    # restart the pipeline rewinds `position` back to this: events that
    # were folded and checkpointed but never made it into a published
    # candidate are re-read into the fresh trainer instead of silently
    # vanishing from the speed layer until the next batch train.
    published_position: list | None = None
    events_read: int = 0
    drains: int = 0
    publishes: int = 0
    last_published_version: str = ""
    last_published_span: str = ""  # span_id_of(...) of the last publish
    last_published_at: str = ""
    updated_at: str = ""

    @staticmethod
    def _pos(raw: list | None) -> Position | None:
        if not raw:
            return None
        return (int(raw[0]), str(raw[1]))

    def pos(self) -> Position | None:
        return self._pos(self.position)

    def published_pos(self) -> Position | None:
        return self._pos(self.published_position)

    def seed(self, position: Position | None) -> None:
        """Set the starting point of a FRESH cursor (e.g. the store head).
        Recorded as both the read position and the publish floor, so a
        crash before the first publish rewinds here, not to the store's
        beginning."""
        raw = [int(position[0]), str(position[1])] if position else None
        self.position = list(raw) if raw else None
        self.published_position = list(raw) if raw else None

    def advance(self, position: Position, n_events: int) -> None:
        self.position = [int(position[0]), str(position[1])]
        self.events_read += n_events
        self.drains += 1

    def record_publish(self, version: str, span_id: str, position: Position) -> None:
        self.publishes += 1
        self.published_position = [int(position[0]), str(position[1])]
        self.last_published_version = version
        self.last_published_span = span_id
        self.last_published_at = _now_iso()

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json_dict(cls, data: dict[str, Any]) -> "StreamCursor":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


def _atomic_write(path: str, data: bytes) -> None:
    """Write-then-rename in the destination directory: readers (and the
    restarted pipeline) see either the old complete file or the new
    complete file, never a prefix."""
    directory = os.path.dirname(path)
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class CursorStore:
    """Per-(app, channel) cursor files under one base directory."""

    def __init__(self, base_dir: str | None = None):
        self.base_dir = os.path.abspath(base_dir or default_stream_dir())
        self._lock = threading.Lock()

    def path(self, app_id: int, channel_id: int | None = None) -> str:
        name = (
            f"cursor_{app_id}.json"
            if channel_id is None
            else f"cursor_{app_id}_{channel_id}.json"
        )
        return os.path.join(self.base_dir, name)

    def load(self, app_id: int, channel_id: int | None = None) -> StreamCursor:
        path = self.path(app_id, channel_id)
        if not os.path.exists(path):
            return StreamCursor(app_id=app_id, channel_id=channel_id)
        try:
            with open(path, encoding="utf-8") as fh:
                cursor = StreamCursor.from_json_dict(json.load(fh))
        except (OSError, ValueError, TypeError):
            logger.warning(
                "unreadable cursor file %s; starting from the beginning", path
            )
            return StreamCursor(app_id=app_id, channel_id=channel_id)
        cursor.app_id = app_id
        cursor.channel_id = channel_id
        return cursor

    def save(self, cursor: StreamCursor) -> None:
        cursor.updated_at = _now_iso()
        with self._lock:
            _atomic_write(
                self.path(cursor.app_id, cursor.channel_id),
                json.dumps(cursor.to_json_dict(), indent=1).encode("utf-8"),
            )
