"""Incremental trainers: fold new events into a servable model in-place.

The :class:`IncrementalTrainer` protocol is what the stream pipeline
drives: ``absorb(events)`` folds a drained micro-batch into the model
state, ``snapshot()`` returns the serializable models list (the same
shape ``workflow/model_io.serialize_models`` persists and serving
deserializes), and ``drift()`` reports the rolling held-out guard — a
breach makes the pipeline suppress the publish instead of shipping a
regressed model.

Three implementations:

- :class:`FoldInALSTrainer` — ALX-style fold-in (PAPERS.md: fold-in of
  new users/items against fixed counterpart factors is exactly the small
  dense solve TPUs crush): per touched entity, rebuild the rank-f normal
  equations from that entity's buffered ratings against the FIXED
  counterpart factors and solve all systems batched through the
  jit-compiled ``ops/spd_solve.batched_spd_solve_auto`` (the same
  Jacobi-CG the batch trainer uses, VMEM-fused on TPU).
- :class:`StreamingNaiveBayesTrainer` — count updates; the categorical
  NB model is a pure function of (label counts, per-position value
  counts), so streaming increments rebuild it exactly.
- :class:`StreamingCooccurrenceTrainer` — incremental pair counts over
  distinct (user, item) interactions; new pairs add 2 counter bumps per
  existing item of the user instead of a full self-join.

Drift guards: every trainer routes a fixed fraction of incoming examples
into a rolling held-out window (never absorbed), and ``drift()`` compares
the CURRENT model against the SEED model on that window — fold-in can
only be published while it is not measurably worse than what is already
stable. The ALS guard additionally checks factor health (non-finite or
exploding norms), which catches corrupt-event poisoning that inflates
both models' held-out error symmetrically.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from collections import Counter, deque
from typing import Any, Protocol, Sequence, runtime_checkable

import numpy as np

from predictionio_tpu.data.event import Event

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class DriftReport:
    """One guard verdict: ``ok=False`` suppresses the publish."""

    ok: bool
    metric: str = ""
    baseline: float | None = None
    current: float | None = None
    reason: str = ""

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class RollingHoldout:
    """Route every ``every``-th offered example into a bounded held-out
    window. Held examples are NOT absorbed — they are the guard's probe
    set, fresh enough to reflect current traffic, old enough to predate
    a poisoning burst (the window spans multiple drains)."""

    def __init__(self, every: int = 8, window: int = 256):
        self.every = max(1, int(every))
        self._n = 0
        self.held: deque = deque(maxlen=max(1, int(window)))

    def offer(self, example: Any) -> bool:
        """True = held out; the caller must skip absorbing it."""
        self._n += 1
        if self._n % self.every == 0:
            self.held.append(example)
            return True
        return False


@runtime_checkable
class IncrementalTrainer(Protocol):
    name: str

    def absorb(self, events: Sequence[Event]) -> int:
        """Fold a micro-batch in; returns the number of examples absorbed
        (held-out and malformed events don't count).

        Implementations may also set ``last_absorb_stats`` — a
        ``{"rows": n, "entities": m}`` dict describing the batch just
        folded (rows = examples absorbed, entities = distinct model
        entities touched) — which the pipeline copies onto the
        ``stream.foldin`` span tags."""

    def snapshot(self) -> list[Any]:
        """The serializable models list (what model_io persists)."""

    def drift(self) -> DriftReport: ...


# ---------------------------------------------------------------------------
# fold-in ALS
# ---------------------------------------------------------------------------


def _rating_of(
    e: Event,
    rating_key: str,
    buy_rating: float,
    rating_map: dict[str, float] | None,
) -> float | None:
    """Per-event mirror of models/recommendation's columnar rating rules."""
    if rating_map is not None:
        if e.event in rating_map:
            return float(rating_map[e.event])
        return None
    if e.event == "buy":
        return buy_rating
    r = e.properties.get_opt(rating_key)
    if isinstance(r, (int, float)) and math.isfinite(float(r)):
        return float(r)
    return None


class FoldInALSTrainer:
    """Fold-in ALS against fixed counterpart factors.

    Per touched user, the trainer buffers that user's stream-seen
    ``(item_idx, rating)`` pairs (bounded, newest kept) and re-solves the
    user's rank-f normal equations ``(V^T W V + reg*n*I) x = V^T W r``
    against the FIXED item table — then symmetrically for touched items
    against the just-updated user table. All touched systems solve in ONE
    batched call through ``ops/spd_solve.batched_spd_solve_auto`` (jit;
    VMEM-fused pallas kernel on TPU). Unknown users/items extend the
    vocab with zero-initialized rows and get real factors on their first
    fold. Degree-scaled regularization matches the batch trainer's ALS-WR
    scheme, so a fold-in of an entity's full rating set reproduces the
    batch half-solve for that entity.
    """

    name = "als-foldin"

    def __init__(
        self,
        models: Sequence[Any],
        *,
        reg: float = 0.1,
        rating_key: str = "rating",
        buy_rating: float = 4.0,
        rating_map: dict[str, float] | None = None,
        max_ratings_per_entity: int = 512,
        holdout_every: int = 8,
        holdout_window: int = 256,
        drift_rmse_ratio: float = 1.5,
        drift_rmse_floor: float = 0.1,
        drift_norm_ratio: float = 10.0,
        drift_min_samples: int = 8,
    ):
        from predictionio_tpu.models.recommendation.engine import ALSModel

        self.models = list(models)
        self._als_index = next(
            (i for i, m in enumerate(self.models) if isinstance(m, ALSModel)),
            None,
        )
        if self._als_index is None:
            raise ValueError("no ALSModel found in the models list")
        seed: ALSModel = self.models[self._als_index]
        self.user_factors = np.asarray(seed.user_factors, np.float32).copy()
        self.item_factors = np.asarray(seed.item_factors, np.float32).copy()
        self.user_vocab = list(seed.user_vocab)
        self.item_vocab = list(seed.item_vocab)
        self._user_index = {u: i for i, u in enumerate(self.user_vocab)}
        self._item_index = {it: i for i, it in enumerate(self.item_vocab)}
        # seed tables kept for the drift guard's side of the comparison
        self._seed_user = self.user_factors.copy()
        self._seed_item = self.item_factors.copy()
        self.reg = float(reg)
        self.rating_key = rating_key
        self.buy_rating = float(buy_rating)
        self.rating_map = dict(rating_map) if rating_map else None
        self.max_ratings_per_entity = max(8, int(max_ratings_per_entity))
        # per-entity stream rating buffers: idx -> deque[(opposite_idx, r)]
        self._user_ratings: dict[int, deque] = {}
        self._item_ratings: dict[int, deque] = {}
        self.holdout = RollingHoldout(holdout_every, holdout_window)
        self.drift_rmse_ratio = drift_rmse_ratio
        self.drift_rmse_floor = drift_rmse_floor
        self.drift_norm_ratio = drift_norm_ratio
        self.drift_min_samples = max(1, drift_min_samples)
        self.examples_absorbed = 0
        self.last_absorb_stats: dict[str, int] = {"rows": 0, "entities": 0}

    # ---------------------------------------------------------------- absorb
    @staticmethod
    def _entity_idx(vocab: list[str], index: dict[str, int], key: str) -> int:
        idx = index.get(key)
        if idx is None:
            idx = len(vocab)
            vocab.append(key)
            index[key] = idx
        return idx

    def _grow_tables(self) -> None:
        """One zero-row extension per side per absorb — a vstack per NEW
        entity would copy the whole table each time (quadratic over a
        catch-up drain full of first-seen users)."""
        for table_attr, vocab in (
            ("user_factors", self.user_vocab),
            ("item_factors", self.item_vocab),
        ):
            table = getattr(self, table_attr)
            grow = len(vocab) - table.shape[0]
            if grow > 0:
                setattr(
                    self,
                    table_attr,
                    np.vstack([table, np.zeros((grow, table.shape[1]), np.float32)]),
                )

    def _buffer(self, buffers: dict[int, deque], idx: int) -> deque:
        buf = buffers.get(idx)
        if buf is None:
            buf = deque(maxlen=self.max_ratings_per_entity)
            buffers[idx] = buf
        return buf

    def absorb(self, events: Sequence[Event]) -> int:
        touched_users: set[int] = set()
        touched_items: set[int] = set()
        absorbed = 0
        for e in events:
            if e.target_entity_id is None:
                continue
            r = _rating_of(e, self.rating_key, self.buy_rating, self.rating_map)
            if r is None:
                continue
            if self.holdout.offer((e.entity_id, e.target_entity_id, r)):
                continue
            uidx = self._entity_idx(self.user_vocab, self._user_index, e.entity_id)
            iidx = self._entity_idx(
                self.item_vocab, self._item_index, e.target_entity_id
            )
            self._buffer(self._user_ratings, uidx).append((iidx, r))
            self._buffer(self._item_ratings, iidx).append((uidx, r))
            touched_users.add(uidx)
            touched_items.add(iidx)
            absorbed += 1
        self._grow_tables()
        if touched_users:
            # users first against the fixed item table, then items against
            # the just-updated users — the classic fold-in ordering
            self._fold(touched_users, self._user_ratings, "user_factors", "item_factors")
        if touched_items:
            self._fold(touched_items, self._item_ratings, "item_factors", "user_factors")
        self.examples_absorbed += absorbed
        self.last_absorb_stats = {
            "rows": absorbed,
            "entities": len(touched_users) + len(touched_items),
        }
        return absorbed

    def _fold(
        self,
        touched: set[int],
        buffers: dict[int, deque],
        solve_attr: str,
        fixed_attr: str,
    ) -> None:
        """Batched rank-f normal-equation solves for the touched entities
        (one jit-compiled SPD solve for the whole set). The result fetch
        rides ``obs.xray.device_fetch`` so a profiled fold-in accounts
        its device stall into the step timeline."""
        from predictionio_tpu.obs import xray
        from predictionio_tpu.ops.spd_solve import batched_spd_solve_auto

        fixed = getattr(self, fixed_attr)
        f = fixed.shape[1]
        prof = xray.current_profile()
        if prof is not None and prof.estimate is None:
            # capacity-planner prediction for the factor tables this
            # fold-in maintains — `pio top`'s est-vs-peak pair (parity
            # with the batch trainer's preflight estimate)
            prof.set_estimate(
                xray.estimate_factors(
                    int(self.user_factors.shape[0]),
                    int(self.item_factors.shape[0]),
                    int(f),
                )
            )
        order = sorted(touched)
        A = np.zeros((len(order), f, f), np.float32)
        b = np.zeros((len(order), f), np.float32)
        eye = np.eye(f, dtype=np.float32)
        for k, idx in enumerate(order):
            pairs = buffers.get(idx)
            if not pairs:
                continue
            opp = np.fromiter((p[0] for p in pairs), np.int64, len(pairs))
            r = np.fromiter((p[1] for p in pairs), np.float32, len(pairs))
            V = fixed[opp]  # [n, f] gather against the FIXED side
            A[k] = V.T @ V + self.reg * max(1.0, len(pairs)) * eye
            b[k] = V.T @ r
        solved = np.asarray(
            xray.device_fetch(batched_spd_solve_auto(A, b), where="foldin-solve"),
            np.float32,
        )
        table = getattr(self, solve_attr)
        table[order] = solved
        setattr(self, solve_attr, table)

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> list[Any]:
        from predictionio_tpu.models.recommendation.engine import ALSModel

        out = list(self.models)
        out[self._als_index] = ALSModel(
            self.user_factors.copy(),
            self.item_factors.copy(),
            list(self.user_vocab),
            list(self.item_vocab),
        )
        self.models = list(out)
        return out

    # ----------------------------------------------------------------- drift
    def _rmse(self, users: np.ndarray, items: np.ndarray, ratings: np.ndarray,
              U: np.ndarray, V: np.ndarray) -> float:
        pred = np.sum(U[users] * V[items], axis=1)
        return float(np.sqrt(np.mean((pred - ratings) ** 2)))

    def drift(self) -> DriftReport:
        # factor-health gate first: corrupt ratings (a poisoned stream)
        # inflate BOTH models' held-out error, but only the folded factors
        # explode — seed-vs-current norms catch what rmse ratios cannot
        if not (
            np.all(np.isfinite(self.user_factors))
            and np.all(np.isfinite(self.item_factors))
        ):
            return DriftReport(False, "factor-health", reason="non-finite factors")
        seed_norm = max(
            1e-6,
            float(np.abs(self._seed_user).max(initial=0.0)),
            float(np.abs(self._seed_item).max(initial=0.0)),
        )
        cur_norm = max(
            float(np.abs(self.user_factors).max(initial=0.0)),
            float(np.abs(self.item_factors).max(initial=0.0)),
        )
        if cur_norm > seed_norm * self.drift_norm_ratio:
            return DriftReport(
                False,
                "factor-health",
                baseline=seed_norm,
                current=cur_norm,
                reason=(
                    f"factor magnitude {cur_norm:.3g} > "
                    f"{self.drift_norm_ratio:g}x seed {seed_norm:.3g}"
                ),
            )
        # held-out rmse gate, restricted to entities BOTH models know (a
        # new user can't regress against a seed that never saw them)
        held = [
            (self._user_index.get(u), self._item_index.get(i), r)
            for u, i, r in self.holdout.held
        ]
        n_seed_u, n_seed_i = self._seed_user.shape[0], self._seed_item.shape[0]
        known = [
            (u, i, r)
            for u, i, r in held
            if u is not None and i is not None and u < n_seed_u and i < n_seed_i
        ]
        if len(known) < self.drift_min_samples:
            return DriftReport(True, "rmse", reason="insufficient held-out samples")
        users = np.asarray([u for u, _, _ in known], np.int64)
        items = np.asarray([i for _, i, _ in known], np.int64)
        ratings = np.asarray([r for _, _, r in known], np.float32)
        baseline = self._rmse(users, items, ratings, self._seed_user, self._seed_item)
        current = self._rmse(users, items, ratings, self.user_factors, self.item_factors)
        ok = current <= baseline * self.drift_rmse_ratio + self.drift_rmse_floor
        return DriftReport(
            ok,
            "rmse",
            baseline=baseline,
            current=current,
            reason="" if ok else (
                f"held-out rmse {current:.4f} > "
                f"{self.drift_rmse_ratio:g}x seed {baseline:.4f} + "
                f"{self.drift_rmse_floor:g}"
            ),
        )


# ---------------------------------------------------------------------------
# streaming naive bayes
# ---------------------------------------------------------------------------


class StreamingNaiveBayesTrainer:
    """Streaming categorical naive Bayes via count updates.

    Events carry ``properties[label_key]`` (string) and
    ``properties[features_key]`` (list of strings). The model is rebuilt
    exactly from the running counts — identical math to
    ``e2.naive_bayes.train_categorical_naive_bayes``.
    """

    name = "naive-bayes-stream"

    def __init__(
        self,
        seed_model=None,
        *,
        label_key: str = "label",
        features_key: str = "features",
        holdout_every: int = 8,
        holdout_window: int = 256,
        drift_max_divergence: float = 0.5,
        drift_min_samples: int = 8,
    ):
        self.label_key = label_key
        self.features_key = features_key
        self._label_counts: Counter = Counter()
        self._value_counts: dict[str, list[Counter]] = {}
        self._n = 0
        self._n_features = 0
        self.holdout = RollingHoldout(holdout_every, holdout_window)
        self.drift_max_divergence = drift_max_divergence
        self.drift_min_samples = max(1, drift_min_samples)
        # NB counts are not recoverable from a log-prob model, so the
        # stream model rebuilds from stream counts — but the STABLE model
        # (when given) anchors the divergence guard: a stream model whose
        # predictions flip away from what is serving cannot publish. Its
        # absence falls back to the first-batch model as the anchor.
        self._seed_model = seed_model
        self._stable_seeded = seed_model is not None
        self.examples_absorbed = 0
        self.last_absorb_stats: dict[str, int] = {"rows": 0, "entities": 0}

    def _extract(self, e: Event):
        from predictionio_tpu.e2.naive_bayes import LabeledPoint

        label = e.properties.get_opt(self.label_key)
        features = e.properties.get_opt(self.features_key)
        if not isinstance(label, str) or not isinstance(features, (list, tuple)):
            return None
        return LabeledPoint(label, tuple(str(v) for v in features))

    def absorb(self, events: Sequence[Event]) -> int:
        absorbed = 0
        touched_labels: set[str] = set()
        for e in events:
            p = self._extract(e)
            if p is None:
                continue
            if self.holdout.offer(p):
                continue
            self._label_counts[p.label] += 1
            self._n += 1
            self._n_features = max(self._n_features, len(p.features))
            per_pos = self._value_counts.setdefault(p.label, [])
            while len(per_pos) < self._n_features:
                per_pos.append(Counter())
            for pos, v in enumerate(p.features):
                per_pos[pos][v] += 1
            touched_labels.add(p.label)
            absorbed += 1
        self.last_absorb_stats = {
            "rows": absorbed,
            "entities": len(touched_labels),
        }
        self.examples_absorbed += absorbed
        if self._seed_model is None and self._n:
            # baseline = the model after the FIRST absorbed batch: later
            # batches must not make held-out accuracy collapse against it
            self._seed_model = self._build_model()
        return absorbed

    def _build_model(self):
        from predictionio_tpu.e2.naive_bayes import CategoricalNaiveBayesModel

        if not self._n:
            raise ValueError("no examples absorbed yet")
        priors = {
            label: math.log(c / self._n) for label, c in self._label_counts.items()
        }
        likelihoods = {
            label: [
                {v: math.log(c / self._label_counts[label]) for v, c in counter.items()}
                for counter in per_pos
            ]
            for label, per_pos in self._value_counts.items()
        }
        return CategoricalNaiveBayesModel(priors, likelihoods)

    def snapshot(self) -> list[Any]:
        return [self._build_model()]

    def drift(self) -> DriftReport:
        """Seed-divergence guard (same idea as the PR-4 shadow-divergence
        gate): the fraction of held-out examples where the folded model's
        prediction DISAGREES with the seed (first-batch) model's. A
        self-consistent poisoning burst fools any accuracy-on-recent-data
        metric (the poison validates itself), but it cannot avoid flipping
        predictions away from the seed."""
        if (
            len(self.holdout.held) < self.drift_min_samples
            or not self._n
            or self._seed_model is None
        ):
            # rebuilt-from-stream NB starts near-empty; with a STABLE
            # model to answer for, a snapshot without held-out evidence
            # must not publish (it would canary a from-scratch model)
            ok = not self._stable_seeded
            return DriftReport(
                ok,
                "divergence",
                reason=(
                    "insufficient held-out samples"
                    if ok
                    else "insufficient held-out evidence to vouch for a "
                    "from-scratch stream model against the stable"
                ),
            )
        current_model = self._build_model()
        held = list(self.holdout.held)
        diverged = sum(
            1
            for p in held
            if current_model.predict(p.features)
            != self._seed_model.predict(p.features)
        )
        rate = diverged / len(held)
        ok = rate <= self.drift_max_divergence
        return DriftReport(
            ok,
            "divergence",
            baseline=0.0,
            current=rate,
            reason="" if ok else (
                f"{rate:.3f} of held-out predictions diverged from the "
                f"seed model (> {self.drift_max_divergence:g})"
            ),
        )


# ---------------------------------------------------------------------------
# streaming cooccurrence
# ---------------------------------------------------------------------------


class StreamingCooccurrenceTrainer:
    """Incremental item-cooccurrence counts over distinct (user, item)
    interactions. A new distinct pair bumps 2 counters per existing item
    of that user (both directions) instead of re-running the self-join.

    Optionally seeded from the similarproduct engine's
    ``CooccurrenceModel``: the stable top-N map's counts merge with the
    stream counts at snapshot time (pairs the stable truncated away are
    gone — documented lossy merge), new items extend the vocab, and
    ``snapshot()`` returns an updated ``CooccurrenceModel``. Unseeded,
    ``snapshot()`` returns the raw string-keyed top-N map
    ``ops/cooccurrence.score_by_cooccurrence`` consumes."""

    name = "cooccurrence-stream"

    def __init__(
        self,
        seed_model=None,
        *,
        top_n: int = 10,
        max_items_per_user: int = 1024,
        holdout_every: int = 8,
        holdout_window: int = 256,
        drift_hit_drop: float = 0.5,
        drift_min_samples: int = 8,
    ):
        self.top_n = max(1, top_n)
        self.max_items_per_user = max(2, max_items_per_user)
        self._user_items: dict[str, set[str]] = {}
        self._pair_counts: Counter = Counter()  # (item_str, item_str) -> n
        self._seed_model = seed_model
        self._seed_counts: Counter = Counter()
        if seed_model is not None:
            vocab = seed_model.item_vocab
            for a, pairs in seed_model.top_map.items():
                for b, c in pairs:
                    self._seed_counts[(vocab[int(a)], vocab[int(b)])] = int(c)
        self.holdout = RollingHoldout(holdout_every, holdout_window)
        self.drift_hit_drop = drift_hit_drop
        self.drift_min_samples = max(1, drift_min_samples)
        self._baseline_hit_rate: float | None = None
        self._top_cache: dict[str, list[tuple[str, int]]] | None = None
        self.examples_absorbed = 0
        self.last_absorb_stats: dict[str, int] = {"rows": 0, "entities": 0}

    def absorb(self, events: Sequence[Event]) -> int:
        absorbed = 0
        touched: set[str] = set()
        for e in events:
            item = e.target_entity_id
            if item is None:
                continue
            user = e.entity_id
            if self.holdout.offer((user, item)):
                continue
            items = self._user_items.setdefault(user, set())
            if item in items or len(items) >= self.max_items_per_user:
                continue  # only DISTINCT interactions count (ref parity)
            for other in items:
                self._pair_counts[(item, other)] += 1
                self._pair_counts[(other, item)] += 1
            items.add(item)
            touched.add(item)
            self._top_cache = None  # counts changed; recompute on demand
            absorbed += 1
        self.last_absorb_stats = {"rows": absorbed, "entities": len(touched)}
        self.examples_absorbed += absorbed
        if self._baseline_hit_rate is None and (
            len(self.holdout.held) >= self.drift_min_samples
        ):
            self._baseline_hit_rate = self._hit_rate()
        return absorbed

    def top_map(self) -> dict[str, list[tuple[str, int]]]:
        """Merged (seed + stream) string-keyed top-N map. Cached until the
        next counted interaction — drift() and snapshot() both need it in
        the same publish attempt, and the merge+sort is O(total pairs)."""
        if self._top_cache is not None:
            return self._top_cache
        merged = self._seed_counts + self._pair_counts
        per_item: dict[str, list[tuple[str, int]]] = {}
        for (a, b), c in merged.items():
            per_item.setdefault(a, []).append((b, c))
        self._top_cache = {
            item: sorted(pairs, key=lambda p: (-p[1], p[0]))[: self.top_n]
            for item, pairs in per_item.items()
        }
        return self._top_cache

    def snapshot(self) -> list[Any]:
        top = self.top_map()
        if self._seed_model is None:
            return [top]
        # rebuild the engine-servable CooccurrenceModel: stream-only items
        # extend the vocab (no categories/properties known for them yet)
        seed = self._seed_model
        vocab = list(seed.item_vocab)
        index = {v: i for i, v in enumerate(vocab)}
        categories = list(seed.item_categories)
        properties = (
            list(seed.item_properties) if seed.item_properties is not None else None
        )

        def idx(item: str) -> int:
            i = index.get(item)
            if i is None:
                i = len(vocab)
                vocab.append(item)
                index[item] = i
                categories.append(None)
                if properties is not None:
                    properties.append(None)
            return i

        int_map = {
            idx(a): [(idx(b), c) for b, c in pairs] for a, pairs in top.items()
        }
        return [
            type(seed)(int_map, vocab, categories, properties)
        ]

    def _hit_rate(self) -> float:
        top = self.top_map()
        held = list(self.holdout.held)
        hits = 0
        for user, item in held:
            others = self._user_items.get(user, set())
            if any(
                item in {o for o, _ in top.get(other, [])} for other in others
            ):
                hits += 1
        return hits / len(held) if held else 0.0

    def drift(self) -> DriftReport:
        if len(self.holdout.held) < self.drift_min_samples:
            return DriftReport(True, "hit-rate", reason="insufficient held-out samples")
        current = self._hit_rate()
        baseline = (
            self._baseline_hit_rate if self._baseline_hit_rate is not None else current
        )
        ok = current >= baseline - self.drift_hit_drop
        return DriftReport(
            ok,
            "hit-rate",
            baseline=baseline,
            current=current,
            reason="" if ok else (
                f"held-out hit rate {current:.3f} dropped more than "
                f"{self.drift_hit_drop:g} below baseline {baseline:.3f}"
            ),
        )


# ---------------------------------------------------------------------------
# streaming sequential (session / next-item)
# ---------------------------------------------------------------------------


class SequentialStreamTrainer:
    """Incremental transition counts over per-user session streams.

    Seeded from the sequential engine's :class:`SequentialModel`: the
    seed's RAW pair counts (kept on the model precisely for this merge —
    ``train_markov_chain`` output alone is top-N-truncated) merge with
    stream counts at snapshot time, and the published model's transition
    matrix is rebuilt through the EXACT ``e2.markov_chain`` math. The
    attention factor tables, when present, ride through unchanged — they
    refresh only at batch retrain (documented in docs/sequential.md); the
    markov scorer is the live-foldable half.

    Events must arrive in session order (the pipeline's ``find_after``
    tail guarantees it); each event extends its user's session, bumping
    one (prev -> item) transition count."""

    name = "sequential-stream"

    def __init__(
        self,
        seed_model=None,
        *,
        top_n: int | None = None,
        max_users: int = 100_000,
        holdout_every: int = 8,
        holdout_window: int = 256,
        drift_hit_drop: float = 0.5,
        drift_min_samples: int = 8,
        instruments=None,
    ):
        self._seed_model = seed_model
        self.top_n = max(
            1, top_n if top_n is not None else getattr(seed_model, "top_n", 10)
        )
        self.max_users = max(16, max_users)
        self._pair_counts: Counter = Counter()  # (item_str, item_str) -> n
        self._user_last: dict[str, str] = {}
        if seed_model is not None:
            vocab = seed_model.item_vocab
            for (i, j), c in seed_model.pair_counts.items():
                self._pair_counts[(vocab[int(i)], vocab[int(j)])] = float(c)
            for u, i in seed_model.user_last.items():
                self._user_last[u] = vocab[int(i)]
        self.holdout = RollingHoldout(holdout_every, holdout_window)
        self.drift_hit_drop = drift_hit_drop
        self.drift_min_samples = max(1, drift_min_samples)
        self._baseline_hit_rate: float | None = None
        self._top_cache: dict[str, list[tuple[str, float]]] | None = None
        self.examples_absorbed = 0
        self.last_absorb_stats: dict[str, int] = {"rows": 0, "entities": 0}
        self._instruments = instruments

    def absorb(self, events: Sequence[Event]) -> int:
        absorbed = 0
        touched: set[str] = set()
        for e in events:
            item = e.target_entity_id
            if item is None or not e.entity_id:
                continue
            user = e.entity_id
            prev = self._user_last.get(user)
            if prev is not None and self.holdout.offer((prev, item)):
                # held-out transitions still advance the session cursor —
                # the NEXT transition's "from" state must stay truthful
                self._user_last[user] = item
                continue
            if prev is not None:
                self._pair_counts[(prev, item)] += 1
                self._top_cache = None
                absorbed += 1
                touched.add(item)
            elif len(self._user_last) >= self.max_users:
                continue  # bounded session-state map: drop NEW users, not counts
            self._user_last[user] = item
        self.last_absorb_stats = {"rows": absorbed, "entities": len(touched)}
        self.examples_absorbed += absorbed
        if self._instruments is not None and absorbed:
            self._instruments.on_absorb(absorbed, len(touched))
        if self._baseline_hit_rate is None and (
            len(self.holdout.held) >= self.drift_min_samples
        ):
            self._baseline_hit_rate = self._hit_rate()
        return absorbed

    def top_map(self) -> dict[str, list[tuple[str, float]]]:
        """Merged top-N transition PROBABILITIES keyed by item string —
        row-normalized and ranked with the identical tie-break the e2
        trainer uses, cached until the next counted transition."""
        if self._top_cache is not None:
            return self._top_cache
        per_item: dict[str, dict[str, float]] = {}
        for (a, b), c in self._pair_counts.items():
            per_item.setdefault(a, {})[b] = per_item.setdefault(a, {}).get(b, 0.0) + c
        out: dict[str, list[tuple[str, float]]] = {}
        for a, row in per_item.items():
            total = sum(row.values())
            if total <= 0:
                continue
            ranked = sorted(
                ((b, c / total) for b, c in row.items()),
                key=lambda t: (-t[1], t[0]),
            )
            out[a] = ranked[: self.top_n]
        self._top_cache = out
        return out

    def snapshot(self) -> list[Any]:
        from predictionio_tpu.models.sequential.engine import (
            SequentialModel,
            markov_from_counts,
        )

        seed = self._seed_model
        vocab = list(seed.item_vocab) if seed is not None else []
        index = {v: i for i, v in enumerate(vocab)}

        def idx(item: str) -> int:
            i = index.get(item)
            if i is None:
                i = len(vocab)
                vocab.append(item)
                index[item] = i
            return i

        counts = {
            (idx(a), idx(b)): float(c) for (a, b), c in self._pair_counts.items()
        }
        model = SequentialModel(
            item_vocab=vocab,
            markov=markov_from_counts(counts, len(vocab), self.top_n),
            pair_counts=counts,
            user_last={u: index[i] for u, i in self._user_last.items()},
            top_n=self.top_n,
            # attention tables refresh only at batch retrain; stream-only
            # items score through the markov path until then
            item_in=getattr(seed, "item_in", None),
            item_out=getattr(seed, "item_out", None),
            context=getattr(seed, "context", 8),
        )
        if self._instruments is not None:
            self._instruments.on_snapshot(
                len(vocab), len(counts), len(self._user_last)
            )
        return [model]

    def _hit_rate(self) -> float:
        top = self.top_map()
        held = list(self.holdout.held)
        hits = 0
        for prev, nxt in held:
            if any(nxt == b for b, _ in top.get(prev, [])):
                hits += 1
        return hits / len(held) if held else 0.0

    def drift(self) -> DriftReport:
        if len(self.holdout.held) < self.drift_min_samples:
            return DriftReport(
                True, "hit-rate", reason="insufficient held-out samples"
            )
        current = self._hit_rate()
        baseline = (
            self._baseline_hit_rate
            if self._baseline_hit_rate is not None
            else current
        )
        ok = current >= baseline - self.drift_hit_drop
        return DriftReport(
            ok,
            "hit-rate",
            baseline=baseline,
            current=current,
            reason="" if ok else (
                f"held-out next-item hit rate {current:.3f} dropped more "
                f"than {self.drift_hit_drop:g} below baseline {baseline:.3f}"
            ),
        )
