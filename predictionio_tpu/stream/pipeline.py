"""StreamPipeline: drain -> fold-in -> publish registry candidates.

The ``pio stream`` driver. Each cycle drains bounded micro-batches from
the :class:`~predictionio_tpu.stream.tailer.EventTailer`, folds them into
the :class:`~predictionio_tpu.stream.trainers.IncrementalTrainer`, and —
when enough new events accumulated and the drift guard is clean —
snapshots the model and publishes it to the PR-4 registry as a
*candidate* (lineage parent = the current stable, train-span = the cursor
interval). The existing rollout machinery (bake gates, candidate breaker)
then decides promote/rollback; the speed layer never hot-swaps stable
(docs/DECISIONS.md).

Exactly-once publish on at-least-once reads: the cursor checkpoints after
every absorbed drain, and each publish carries a deterministic span id
derived from the cursor interval it covers. Before publishing, the
registry's manifests are consulted for that span id — a crash replay of
the same interval recognizes the existing candidate instead of minting a
second one (docs/streaming.md walks the two crash windows).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable

from predictionio_tpu.obs import xray
from predictionio_tpu.obs.jaxprof import CompileWatcher
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import get_tracer
from predictionio_tpu.registry import ArtifactStore, ModelManifest
from predictionio_tpu.registry.store import MODE_CANARY, MODE_SHADOW
from predictionio_tpu.resilience import CircuitOpenError
from predictionio_tpu.stream.cursor import CursorStore, span_id_of
from predictionio_tpu.stream.tailer import EventTailer
from predictionio_tpu.stream.trainers import IncrementalTrainer
from predictionio_tpu.workflow import model_io

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class StreamConfig:
    """Pipeline knobs (docs/streaming.md)."""

    engine_id: str
    engine_version: str = ""
    engine_variant: str = ""
    engine_factory: str = ""
    # rollout shape of published candidates
    mode: str = MODE_CANARY
    fraction: float = 0.1
    # publish when at least this many new events folded since last publish
    publish_min_events: int = 1
    # drains per run_once cycle (bounds a catch-up burst after downtime)
    max_batches_per_cycle: int = 100
    keep_versions: int = 20
    # run_forever pacing
    interval_s: float = 5.0
    breaker_pause_s: float = 5.0

    def __post_init__(self):
        if self.mode not in (MODE_CANARY, MODE_SHADOW):
            raise ValueError(f"mode must be canary|shadow, got {self.mode!r}")


class StreamInstruments:
    """The ``pio_stream_*`` metric family (rendered by both servers'
    /metrics when the pipeline shares their registry, and by ``pio top``)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.events = r.counter(
            "pio_stream_events_total", "events drained from the event store"
        )
        self.drains = r.counter("pio_stream_drains_total", "micro-batch drains")
        self.publishes = r.counter(
            "pio_stream_publishes_total", "registry candidates published"
        )
        self.drift_suppressed = r.counter(
            "pio_stream_drift_suppressed_total",
            "publishes suppressed by the drift guard",
        )
        self.errors = r.counter(
            "pio_stream_errors_total", "pipeline cycle errors", labelnames=("stage",)
        )
        self.lag_events = r.gauge(
            "pio_stream_lag_events", "events behind the store head (probe-capped)"
        )
        self.lag_seconds = r.gauge(
            "pio_stream_lag_seconds", "age of the oldest unprocessed event"
        )
        self.last_publish_ts = r.gauge(
            "pio_stream_last_publish_timestamp",
            "unix time of the last registry publish",
        )
        self.foldin_seconds = r.histogram(
            "pio_stream_foldin_seconds", "fold-in wall time per drained batch"
        )
        self.drain_seconds = r.histogram(
            "pio_stream_drain_seconds", "drain wall time per micro-batch"
        )
        # jit cache-miss watching for the fold-in loop: vocab growth in a
        # stream trainer re-shapes the batched solve and retriggers XLA
        # compiles — invisible until now because only serving processes
        # carried a CompileWatcher. Sampled at every scrape (collector)
        # and after every pipeline cycle; `pio top` renders the count on
        # the stream line.
        self.compile_watcher = CompileWatcher(r)
        r.register_collector(self.compile_watcher.sample)
        # the pio_train_* family exists (zero series) from process start:
        # the fold-in profiler fills it, scrapers and the docs contract
        # see it immediately
        xray.register_train_metrics(r)
        # the pio_ann_* family: the stream layer is the index's refresh
        # producer (refreshes/rebuilds count here; the serving-side
        # query/recall instruments ride the query server's registry)
        from predictionio_tpu.ann.metrics import AnnInstruments

        self.ann = AnnInstruments(r)
        # the pio_seq_* family: the stream layer is where sessions fold in
        # (the sequential trainer binds to this on pipeline construction)
        from predictionio_tpu.models.sequential.metrics import SeqInstruments

        self.seq = SeqInstruments(r)


class StreamPipeline:
    """One tailed (app, channel) feeding one incremental trainer."""

    def __init__(
        self,
        tailer: EventTailer,
        trainer: IncrementalTrainer,
        cursors: CursorStore,
        store: ArtifactStore | None,
        config: StreamConfig,
        *,
        instruments: StreamInstruments | None = None,
        tracer=None,
        stage_hook: Callable[[str, str, float], None] | None = None,
        clock: Callable[[], float] = time.time,
        ring=None,  # obs.tsring.TelemetryRing
        incidents=None,  # obs.incidents.IncidentRecorder
    ):
        self.tailer = tailer
        self.trainer = trainer
        self.cursors = cursors
        self.store = store
        self.config = config
        self.instruments = instruments or StreamInstruments()
        # bind the pio_seq_* family to a sequential trainer that was built
        # without one (only SequentialStreamTrainer carries the slot)
        if getattr(trainer, "_instruments", False) is None:
            trainer._instruments = self.instruments.seq
        self.tracer = tracer or get_tracer()
        # stage_hook(version, mode, fraction) overrides direct registry
        # staging — `pio stream --notify-url` posts /models/candidate to a
        # live server so the candidate lane starts baking immediately
        self.stage_hook = stage_hook
        self._clock = clock
        # drift breaches are structured signals, not just a counter: each
        # one lands on the telemetry ring (kind="drift") where the
        # lifecycle controller reads it as its primary retune sensor, and
        # fires a rate-limited incident bundle (the recorder's per-kind
        # min-interval keeps a flapping guard from flooding the disk)
        self.ring = ring
        self.incidents = incidents
        self.cursor = cursors.load(tailer.app_id, tailer.channel_id)
        # Restart rewind: events folded and checkpointed but never
        # PUBLISHED live only in the dead process's trainer, so resume
        # from the last published position (or the initial seed) and
        # re-fold them into this fresh trainer — at-least-once reads in
        # exchange for never losing events to the speed layer. The span
        # dedup keeps the replay from double-publishing.
        if self.cursor.position != self.cursor.published_position:
            logger.info(
                "rewinding cursor to the last published position "
                "(re-folding the unpublished tail)"
            )
            self.cursor.position = (
                list(self.cursor.published_position)
                if self.cursor.published_position
                else None
            )
            cursors.save(self.cursor)
        # events folded since the last publish attempt's span start
        self._span_from = self.cursor.pos()
        self._pending_events = 0
        self._pending_absorbed = 0
        # the fold-in step profiler: one TrainProfile per publish span
        # (created at the first drain after a publish, finished into the
        # candidate's manifest) — wall accumulates only inside run_once,
        # so run_forever's sleeps never dilute the tiling contract
        self._profile: xray.TrainProfile | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------------ run
    def _ensure_profile(self) -> xray.TrainProfile:
        if self._profile is None or self._profile.finished:
            self._profile = xray.TrainProfile(
                trainer=self.trainer.name,
                registry=self.instruments.registry,
                tracer=self.tracer,
            )
        return self._profile

    def run_once(self) -> dict[str, Any]:
        """One cycle: drain until caught up (bounded), fold, maybe publish.
        Returns a JSON-ready summary.

        Profiler phases (obs/xray): drains, checkpoints, and the lag
        probe account as ``host_etl``; each fold-in is one ``sweep``
        step; the drift guard is ``eval``; snapshot+serialize is
        ``host_etl`` again — together they tile the cycle's wall clock
        (the stream half of the tiling contract test)."""
        ins = self.instruments
        prof = self._ensure_profile()
        with xray.use_profile(prof), prof.measure():
            summary = self._cycle(prof)
        ins.compile_watcher.sample()
        return summary

    def _cycle(self, prof: xray.TrainProfile) -> dict[str, Any]:
        ins = self.instruments
        drained = 0
        backlog = False
        for _ in range(self.config.max_batches_per_cycle):
            t0 = time.perf_counter()
            with prof.phase(xray.PHASE_HOST_ETL):
                result = self.tailer.drain(self.cursor.pos())
            if not result.events:
                break
            # empty polls don't count: drains_total means batches that
            # actually moved events (pio top derives drains/s from it)
            ins.drain_seconds.observe(time.perf_counter() - t0)
            ins.drains.inc()
            with self.tracer.span(
                "stream.foldin", kind="stream", trainer=self.trainer.name
            ) as sp, prof.step(events=len(result.events)) as rec:
                t1 = time.perf_counter()
                with prof.phase(xray.PHASE_SWEEP):
                    absorbed = self.trainer.absorb(result.events)
                ins.foldin_seconds.observe(time.perf_counter() - t1)
                sp.tags["events"] = len(result.events)
                sp.tags["absorbed"] = absorbed
                stats = getattr(self.trainer, "last_absorb_stats", None)
                if stats:
                    # row/entity-touched cardinality from the trainer —
                    # the fold's solve size scales with entities touched,
                    # not with the raw event count
                    sp.tags["rows"] = stats.get("rows")
                    sp.tags["entities"] = stats.get("entities")
                    rec["entities"] = stats.get("entities")
                rec["metric"] = absorbed
                prof.add_rows(absorbed)
            drained += len(result.events)
            ins.events.inc(len(result.events))
            self._pending_events += len(result.events)
            self._pending_absorbed += absorbed
            # checkpoint AFTER the fold: a crash between fold and save
            # re-reads this drain (at-least-once); a crash before the fold
            # loses nothing
            with prof.phase(xray.PHASE_HOST_ETL):
                self.cursor.advance(result.position, len(result.events))
                self.cursors.save(self.cursor)
            backlog = result.more
            if not result.more:
                break
        with prof.phase(xray.PHASE_HOST_ETL):
            lag_n, lag_s = self.tailer.lag(
                self.cursor.pos(), assume_backlog=backlog
            )
            prof.sample_memory()
        ins.lag_events.set(lag_n)
        ins.lag_seconds.set(lag_s)
        published, suppressed = None, False
        if (
            self.store is not None
            and self._pending_events >= self.config.publish_min_events
            # at least one event must have actually FOLDED: a span of
            # unusable events (wrong shape, held out) would republish an
            # unchanged — or for a fresh NB trainer, unbuildable — model
            and self._pending_absorbed > 0
            and self.cursor.pos() is not None
        ):
            published, suppressed = self._maybe_publish()
        return {
            "drained": drained,
            "pendingEvents": self._pending_events,
            "lagEvents": lag_n,
            "lagSeconds": round(lag_s, 3),
            "published": published,
            "driftSuppressed": suppressed,
            "cursor": self.cursor.to_json_dict(),
        }

    def run_forever(
        self, max_cycles: int | None = None, sleep: Callable[[float], None] = time.sleep
    ) -> None:
        """The ``pio stream`` loop: cycle, pause, repeat. A tripped tail
        breaker pauses for its recovery window instead of spinning; other
        errors are counted and the loop keeps going."""
        cycles = 0
        while not self._stop.is_set():
            try:
                summary = self.run_once()
                if summary["published"]:
                    logger.info(
                        "stream published %s (%d events this cycle)",
                        summary["published"],
                        summary["drained"],
                    )
            except CircuitOpenError as exc:
                logger.warning("tail breaker open, pausing: %s", exc)
                self.instruments.errors.inc(stage="drain")
                sleep(self.config.breaker_pause_s)
            except Exception:
                logger.exception("stream cycle failed")
                self.instruments.errors.inc(stage="cycle")
            cycles += 1
            if max_cycles is not None and cycles >= max_cycles:
                return
            if self._stop.is_set():
                return
            sleep(self.config.interval_s)

    def stop(self) -> None:
        self._stop.set()

    # -------------------------------------------------------------- publish
    def _find_published_span(self, span_id: str) -> ModelManifest | None:
        """Registry-side dedup: the manifest already covering this cursor
        interval, if a crashed prior run published it."""
        for m in self.store.list_versions(self.config.engine_id):
            if m.data_span.get("stream", {}).get("spanId") == span_id:
                return m
        return None

    def _signal_drift(self, report) -> None:
        """A breached guard is the lifecycle controller's primary sensor:
        one structured ring record per suppressed publish (engine,
        trainer, guard, measured-vs-threshold) plus a rate-limited
        incident bundle. Never raises — the publish suppression already
        happened and the stream loop must keep folding."""
        detail = {
            "engine": self.config.engine_id,
            "trainer": self.trainer.name,
            "guard": report.metric,
            "measured": report.current,
            "threshold": report.baseline,
            "reason": report.reason,
        }
        if self.ring is not None:
            try:
                self.ring.append({"kind": "drift", **detail})
            except Exception:
                logger.exception("drift signal: ring append failed")
        if self.incidents is not None:
            # trigger() is internally rate-limited per kind and never
            # raises; the bundle snapshots the ring tail around the breach
            self.incidents.trigger("stream-drift", context=detail)

    def _maybe_publish(self) -> tuple[str | None, bool]:
        cfg = self.config
        span_to = self.cursor.pos()
        span_id = span_id_of(self._span_from, span_to)
        prof = self._profile
        with self.tracer.span(
            "stream.publish", kind="stream", engine_id=cfg.engine_id
        ) as sp:
            sp.tags["spanId"] = span_id
            with xray.phase(xray.PHASE_EVAL):
                report = self.trainer.drift()
            if not report.ok:
                sp.status = "drift-suppressed"
                sp.tags["reason"] = report.reason
                self.instruments.drift_suppressed.inc()
                logger.warning(
                    "drift guard breached; publish suppressed: %s", report.reason
                )
                self._signal_drift(report)
                return None, True
            existing = self._find_published_span(span_id)
            if existing is not None:
                # a crashed prior run already published this interval:
                # recognize it instead of minting a duplicate candidate —
                # but DO re-stage it (the crash may have landed between
                # publish and stage; _stage is a no-op for the auto-stable
                # first publish and tolerates an already-staged version).
                # The replayed span keeps the manifest's original profile;
                # this run's re-fold evidence is discarded with its span.
                sp.tags["deduped"] = True
                version = existing.version
                if prof is not None:
                    prof.finish()
                    self._profile = None
                self._stage(version)
            else:
                with xray.phase(xray.PHASE_HOST_ETL):
                    models = self.trainer.snapshot()
                    blob = model_io.serialize_models(models)
                # the fold-in profile is this candidate's training
                # evidence: finished here (publish I/O is outside it by
                # causality — the manifest must embed a closed profile),
                # attached both as the manifest's train_profile and under
                # data_span.stream for parity with the batch path
                profile_json: dict[str, Any] = {}
                if prof is not None:
                    profile_json = prof.finish().to_json_dict()
                    self._profile = None
                state = self.store.get_state(cfg.engine_id)
                manifest = self.store.publish(
                    ModelManifest(
                        version="",
                        engine_id=cfg.engine_id,
                        engine_version=cfg.engine_version,
                        engine_variant=cfg.engine_variant,
                        engine_factory=cfg.engine_factory,
                        parent_version=state.stable,
                        data_span={
                            "stream": {
                                "spanId": span_id,
                                "from": list(self._span_from)
                                if self._span_from
                                else None,
                                "to": list(span_to),
                                "events": self._pending_events,
                                "trainer": self.trainer.name,
                                "drift": report.to_json_dict(),
                                "profile": profile_json,
                            }
                        },
                        metrics={"driftMetric": report.metric},
                        train_profile=profile_json,
                    ),
                    blob,
                    keep_last=cfg.keep_versions,
                )
                version = manifest.version
                # refresh the parent's ANN index for this candidate BEFORE
                # staging: the lane loader reads the manifest at stage
                # time, so the index must be pinned first. Same
                # publish-as-candidate discipline as the model — the
                # refreshed index bakes with its candidate and can never
                # hot-swap into stable on its own.
                self._refresh_ann(state.stable, version, models)
                self._stage(version)
            sp.tags["version"] = version
        self.cursor.record_publish(version, span_id, span_to)
        self.cursors.save(self.cursor)
        self.instruments.publishes.inc()
        self.instruments.last_publish_ts.set(self._clock())
        self._span_from = span_to
        self._pending_events = 0
        self._pending_absorbed = 0
        return version, False

    def _refresh_ann(self, parent_version: str, version: str, models) -> None:
        """Carry the stable version's ANN index forward onto the freshly
        published candidate (incremental rebucket, or a drift-guarded
        full rebuild — ann/lifecycle). Best-effort: a failed refresh
        leaves the candidate serving exact, never blocks the publish."""
        try:
            from predictionio_tpu.ann import lifecycle as ann_lifecycle

            ann_lifecycle.refresh_for_publish(
                self.store,
                self.config.engine_id,
                parent_version,
                version,
                models,
                instruments=self.instruments.ann,
            )
        except Exception:
            logger.exception(
                "ann index refresh failed (candidate %s serves exact)", version
            )
            self.instruments.errors.inc(stage="ann")

    def _stage(self, version: str) -> None:
        """Hand the published version to the rollout path. The first ever
        publish auto-became stable inside ``ArtifactStore.publish`` (there
        is nothing to canary against), so only stage when it didn't."""
        state = self.store.get_state(self.config.engine_id)
        if state.stable == version:
            return
        if self.stage_hook is not None:
            self.stage_hook(version, self.config.mode, self.config.fraction)
            return
        try:
            self.store.stage_candidate(
                self.config.engine_id,
                version,
                mode=self.config.mode,
                fraction=self.config.fraction,
            )
        except ValueError as exc:
            # e.g. an operator staged something else concurrently; the
            # candidate stays published and listable either way
            logger.warning("stage skipped for %s: %s", version, exc)

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> dict[str, Any]:
        return {
            "engineId": self.config.engine_id,
            "trainer": self.trainer.name,
            "cursor": self.cursor.to_json_dict(),
            "pendingEvents": self._pending_events,
            "tailer": self.tailer.snapshot(),
        }


def serve_metrics(registry: MetricsRegistry, port: int, host: str = "0.0.0.0"):
    """Expose a registry at ``GET /metrics`` from a daemon thread — the
    scrape surface for a standalone ``pio stream`` process (the query/
    event servers render their own registries; a pipeline sharing one of
    those needs nothing). Stdlib http.server: the pipeline loop must not
    depend on an event loop. Returns the server; ``shutdown()`` stops it."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server contract
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = registry.render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes are not operator news
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="stream-metrics", daemon=True
    )
    thread.start()
    return server


def trainer_for_models(models: list[Any], **kwargs: Any) -> IncrementalTrainer:
    """Pick the incremental trainer matching a deserialized models list
    (what the registry blob holds), seeded from the stable model so the
    stream continues FROM what serves rather than from scratch. Raises
    when no model type has a fold-in implementation."""
    from predictionio_tpu.e2.naive_bayes import CategoricalNaiveBayesModel
    from predictionio_tpu.models.recommendation.engine import ALSModel
    from predictionio_tpu.models.sequential.engine import SequentialModel
    from predictionio_tpu.models.similarproduct.engine import CooccurrenceModel
    from predictionio_tpu.stream.trainers import (
        FoldInALSTrainer,
        SequentialStreamTrainer,
        StreamingCooccurrenceTrainer,
        StreamingNaiveBayesTrainer,
    )

    for m in models:
        if isinstance(m, SequentialModel):
            return SequentialStreamTrainer(m, **kwargs)
    for m in models:
        if isinstance(m, ALSModel):
            return FoldInALSTrainer(models, **kwargs)
    for m in models:
        if isinstance(m, CategoricalNaiveBayesModel):
            # counts are unrecoverable from a log-prob model: the stream
            # model rebuilds from stream counts, with the stable model
            # anchoring the divergence drift guard (trainers.py)
            return StreamingNaiveBayesTrainer(m, **kwargs)
    for m in models:
        if isinstance(m, CooccurrenceModel):
            return StreamingCooccurrenceTrainer(m, **kwargs)
    raise ValueError(
        "no incremental trainer for model types "
        f"{[type(m).__name__ for m in models]}; fold-in is implemented for "
        "SequentialModel, ALSModel, CategoricalNaiveBayesModel, and "
        "CooccurrenceModel"
    )
