"""First-order Markov chain over item transitions.

Reference parity: ``e2/.../engine/MarkovChain.scala:26-55`` — build a
row-normalized transition model from coordinate (i, j, count) data, keeping
only the top-N outgoing probabilities per state.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Sequence


@dataclasses.dataclass
class MarkovChainModel:
    n_states: int
    top_n: int
    # state -> [(next_state, probability)] sorted desc, length <= top_n
    transitions: dict[int, list[tuple[int, float]]]

    def transition_probs(self, state: int) -> list[tuple[int, float]]:
        return self.transitions.get(state, [])

    def predict(self, state: int) -> int | None:
        probs = self.transition_probs(state)
        return probs[0][0] if probs else None


def train_markov_chain(
    coordinates: Sequence[tuple[int, int, float]],
    n_states: int,
    top_n: int,
) -> MarkovChainModel:
    """coordinates = (from_state, to_state, count) triples (duplicates
    summed)."""
    rows: dict[int, dict[int, float]] = defaultdict(lambda: defaultdict(float))
    for i, j, c in coordinates:
        rows[i][j] += c
    transitions: dict[int, list[tuple[int, float]]] = {}
    for i, counts in rows.items():
        total = sum(counts.values())
        if total <= 0:
            continue
        ranked = sorted(
            ((j, c / total) for j, c in counts.items()),
            key=lambda t: (-t[1], t[0]),
        )
        transitions[i] = ranked[:top_n]
    return MarkovChainModel(n_states, top_n, transitions)
