"""Categorical naive Bayes on string-valued features.

Reference parity: ``e2/.../engine/CategoricalNaiveBayes.scala:29-170`` —
train computes class priors and per-(feature-position, value) conditional
log-likelihoods with add-one smoothing absent (the reference scores unseen
values via a default likelihood); ``predict`` returns the argmax label,
``log_score`` exposes the raw joint log-probability with a pluggable default
for unseen feature values.
"""

from __future__ import annotations

import dataclasses
import math
from collections import Counter, defaultdict
from typing import Callable, Sequence


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    label: str
    features: tuple[str, ...]


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    priors: dict[str, float]  # label -> log prior
    likelihoods: dict[str, list[dict[str, float]]]  # label -> per-pos {value: log p}

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Sequence[float]], float] = lambda _: float(
            "-inf"
        ),
    ) -> float | None:
        """Joint log probability of the point under its label; None when the
        label itself is unknown (ref logScore :121-138)."""
        if point.label not in self.priors:
            return None
        return self._log_score_internal(point.label, point.features, default_likelihood)

    def _log_score_internal(self, label, features, default_likelihood) -> float:
        ll = self.likelihoods[label]
        score = self.priors[label]
        for pos, value in enumerate(features):
            table = ll[pos] if pos < len(ll) else {}
            if value in table:
                score += table[value]
            else:
                score += default_likelihood(list(table.values()))
        return score

    def predict(self, features: Sequence[str]) -> str:
        """argmax over labels (ref predict :87-103)."""
        best, best_score = None, float("-inf")
        for label in self.priors:
            s = self._log_score_internal(
                label, tuple(features), lambda _: float("-inf")
            )
            if s > best_score or best is None:
                best, best_score = label, s
        return best  # type: ignore[return-value]


def train_categorical_naive_bayes(
    points: Sequence[LabeledPoint],
) -> CategoricalNaiveBayesModel:
    if not points:
        raise ValueError("cannot train on an empty dataset")
    label_counts: Counter[str] = Counter(p.label for p in points)
    n = len(points)
    n_features = max(len(p.features) for p in points)
    # per label, per position, value counts
    value_counts: dict[str, list[Counter]] = defaultdict(
        lambda: [Counter() for _ in range(n_features)]
    )
    for p in points:
        vc = value_counts[p.label]
        for pos, v in enumerate(p.features):
            vc[pos][v] += 1
    priors = {label: math.log(c / n) for label, c in label_counts.items()}
    likelihoods: dict[str, list[dict[str, float]]] = {}
    for label, per_pos in value_counts.items():
        total = label_counts[label]
        likelihoods[label] = [
            {v: math.log(c / total) for v, c in counter.items()}
            for counter in per_pos
        ]
    return CategoricalNaiveBayesModel(priors, likelihoods)
