"""Engine-building algorithm library ("e2").

Reference parity: ``e2/src/main/scala/org/apache/predictionio/e2/`` —
``CategoricalNaiveBayes`` (:23-170), ``MarkovChain`` (:26-55),
``BinaryVectorizer`` (:26-60), ``CrossValidation.splitData`` (:25-67).
Spark ``combineByKey``/``CoordinateMatrix`` plumbing is replaced by numpy /
jax reductions.
"""

from predictionio_tpu.e2.naive_bayes import (
    CategoricalNaiveBayesModel,
    LabeledPoint,
    train_categorical_naive_bayes,
)
from predictionio_tpu.e2.markov_chain import MarkovChainModel, train_markov_chain
from predictionio_tpu.e2.vectorizer import BinaryVectorizer
from predictionio_tpu.e2.cross_validation import k_fold_split

__all__ = [
    "BinaryVectorizer",
    "CategoricalNaiveBayesModel",
    "LabeledPoint",
    "MarkovChainModel",
    "k_fold_split",
    "train_categorical_naive_bayes",
    "train_markov_chain",
]
