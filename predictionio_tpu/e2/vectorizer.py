"""Binary one-hot vectorizer over (property, value) pairs.

Reference parity: ``e2/.../engine/BinaryVectorizer.scala:26-60`` — build a
(property, value) -> column index from observed maps, then encode a map into
a dense 0/1 vector.
"""

from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np


class BinaryVectorizer:
    def __init__(self, index: dict[tuple[str, str], int]):
        self.index = dict(index)

    @property
    def n_features(self) -> int:
        return len(self.index)

    @staticmethod
    def fit(
        maps: Iterable[Mapping[str, str]], properties: Iterable[str] | None = None
    ) -> "BinaryVectorizer":
        props = set(properties) if properties is not None else None
        seen: dict[tuple[str, str], int] = {}
        for m in maps:
            for k, v in m.items():
                if props is not None and k not in props:
                    continue
                seen.setdefault((k, str(v)), len(seen))
        return BinaryVectorizer(seen)

    def transform(self, m: Mapping[str, str]) -> np.ndarray:
        out = np.zeros(len(self.index), dtype=np.float32)
        for k, v in m.items():
            idx = self.index.get((k, str(v)))
            if idx is not None:
                out[idx] = 1.0
        return out
