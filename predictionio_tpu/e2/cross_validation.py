"""k-fold splitting helper.

Reference parity: ``e2/.../evaluation/CrossValidation.scala:25-67``
(``CommonHelperFunctions.splitData``): fold membership by index modulo k,
yielding (training, testing) per fold.
"""

from __future__ import annotations

from typing import Sequence, TypeVar

T = TypeVar("T")


def k_fold_split(data: Sequence[T], k: int) -> list[tuple[list[T], list[T]]]:
    if k <= 0:
        raise ValueError("k must be positive")
    if k > len(data):
        # every fold past len(data) would have an EMPTY test split: its
        # metric scores 0/NaN and the degenerate cell can silently drag a
        # grid-search average (the evaluation grid clamps k with a warning
        # BEFORE calling this — predictionio_tpu/tuning/grid.clamp_folds)
        raise ValueError(
            f"k={k} folds over {len(data)} records would yield empty test "
            f"folds; use k <= {len(data)} (tuning.grid.clamp_folds clamps)"
        )
    folds = []
    for fold in range(k):
        train = [x for i, x in enumerate(data) if i % k != fold]
        test = [x for i, x in enumerate(data) if i % k == fold]
        folds.append((train, test))
    return folds
