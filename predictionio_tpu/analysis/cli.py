"""Console entry for the analyzer: ``pio lint`` and the standalone ``lint``.

Deliberately free of jax/numpy imports so it starts fast in CI and
pre-commit hooks (and cannot hang on a wedged accelerator tunnel).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from predictionio_tpu.analysis import LintConfig, all_rules, analyze_paths


def default_lint_paths() -> list[str]:
    """The package itself, the bundled engine templates (inside it) and the
    examples/ tree next to the repo root, when present."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg_dir)
    paths = [pkg_dir]
    examples = os.path.join(root, "examples")
    if os.path.isdir(examples):
        paths.append(examples)
    return paths


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the installed "
        "predictionio_tpu package and ./examples)",
    )
    parser.add_argument(
        "--format",
        dest="output_format",
        choices=("text", "json"),
        default="text",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero on warnings too, not just errors",
    )
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE-ID",
        help="run only these rule ids (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule registry and exit"
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print findings silenced by pio-lint comments",
    )


def run_lint(args) -> int:
    if args.list_rules:
        for meta in all_rules():
            print(
                f"{meta.id:<28} {meta.severity.name.lower():<8} "
                f"[{meta.family}] {meta.summary}"
            )
        return 0
    paths = args.paths or default_lint_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"[ERROR] no such path: {p}", file=sys.stderr)
            return 2
    if args.rules:
        known = {m.id for m in all_rules()}
        unknown = sorted(set(args.rules) - known)
        if unknown:
            # a typo'd --rule must not neuter the gate while looking green
            print(
                f"[ERROR] unknown rule id(s): {', '.join(unknown)} "
                f"(see --list-rules)",
                file=sys.stderr,
            )
            return 2
    config = LintConfig(
        enabled=frozenset(args.rules) if args.rules else None,
    )
    report = analyze_paths(paths, config=config)
    if args.output_format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_json_dict() for f in report.findings],
                    "suppressed": [f.to_json_dict() for f in report.suppressed],
                    "files_scanned": report.files_scanned,
                    "duration_s": round(report.duration_s, 3),
                },
                indent=2,
            )
        )
    else:
        for f in report.findings:
            print(f.format())
        if args.show_suppressed:
            for f in report.suppressed:
                print(f"{f.format()}  (suppressed)")
        print(report.summary())
    failed = bool(report.errors) or (args.strict and report.warnings)
    return 1 if failed else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="lint",
        description="TPU-aware static analyzer for predictionio_tpu code "
        "(tracer safety, recompile hazards, host-sync stalls, concurrency, "
        "storage contracts)",
    )
    add_lint_arguments(parser)
    return run_lint(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
