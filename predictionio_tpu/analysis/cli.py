"""Console entry for the analyzer: ``pio lint`` and the standalone ``lint``.

Deliberately free of jax/numpy imports so it starts fast in CI and
pre-commit hooks (and cannot hang on a wedged accelerator tunnel).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from predictionio_tpu.analysis import LintConfig, all_rules, analyze_paths


def default_lint_paths() -> list[str]:
    """The package itself, the bundled engine templates (inside it) and the
    examples/ tree next to the repo root, when present."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg_dir)
    paths = [pkg_dir]
    examples = os.path.join(root, "examples")
    if os.path.isdir(examples):
        paths.append(examples)
    return paths


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the installed "
        "predictionio_tpu package and ./examples)",
    )
    parser.add_argument(
        "--format",
        dest="output_format",
        choices=("text", "json", "sarif"),
        default="text",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero on warnings too, not just errors",
    )
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE-ID",
        help="run only these rule ids (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule registry and exit"
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print findings silenced by pio-lint comments",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help="report findings only for files changed vs git HEAD "
        "(+ untracked); the call graph is still built whole-program, so "
        "reachability stays correct",
    )
    parser.add_argument(
        "--report-suppressions",
        action="store_true",
        help="print the suppression inventory (every # pio-lint: disable "
        "site, used or STALE, with its reason) instead of findings",
    )


def _git_changed_files() -> list[str] | None:
    """Absolute paths of .py files changed vs HEAD plus untracked ones,
    or None when git itself fails (not a repo, no git binary)."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True,
            text=True,
            check=True,
        ).stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD", "--"],
            capture_output=True,
            text=True,
            check=True,
            cwd=top,
        ).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True,
            text=True,
            check=True,
            cwd=top,
        ).stdout
    except (OSError, subprocess.CalledProcessError):
        return None
    out: list[str] = []
    for rel in (diff + untracked).splitlines():
        rel = rel.strip()
        if rel.endswith(".py"):
            out.append(os.path.join(top, rel))
    return out


_SARIF_LEVEL = {"ERROR": "error", "WARNING": "warning"}


def to_sarif(report) -> dict:
    """SARIF 2.1.0 — one run, the full rule registry as tool metadata,
    one result per active finding."""
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "pio-lint",
                        "informationUri": (
                            "docs/static_analysis.md"
                        ),
                        "rules": [
                            {
                                "id": m.id,
                                "shortDescription": {"text": m.summary},
                                "defaultConfiguration": {
                                    "level": _SARIF_LEVEL.get(
                                        m.severity.name, "note"
                                    )
                                },
                                "properties": {"family": m.family},
                            }
                            for m in all_rules()
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": _SARIF_LEVEL.get(f.severity.name, "note"),
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {
                                        "uri": f.path.replace(os.sep, "/")
                                    },
                                    "region": {
                                        "startLine": f.line,
                                        "startColumn": f.col + 1,
                                    },
                                }
                            }
                        ],
                    }
                    for f in report.findings
                ],
            }
        ],
    }


def run_lint(args) -> int:
    if args.list_rules:
        for meta in all_rules():
            print(
                f"{meta.id:<28} {meta.severity.name.lower():<8} "
                f"[{meta.family}] {meta.summary}"
            )
        return 0
    paths = args.paths or default_lint_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"[ERROR] no such path: {p}", file=sys.stderr)
            return 2
    if args.rules:
        known = {m.id for m in all_rules()}
        unknown = sorted(set(args.rules) - known)
        if unknown:
            # a typo'd --rule must not neuter the gate while looking green
            print(
                f"[ERROR] unknown rule id(s): {', '.join(unknown)} "
                f"(see --list-rules)",
                file=sys.stderr,
            )
            return 2
    report_paths = None
    if args.changed:
        changed = _git_changed_files()
        if changed is None:
            print(
                "[ERROR] --changed needs a git checkout (git diff failed)",
                file=sys.stderr,
            )
            return 2
        if not changed:
            print("no changed python files vs HEAD")
            return 0
        report_paths = changed
    config = LintConfig(
        enabled=frozenset(args.rules) if args.rules else None,
    )
    report = analyze_paths(paths, config=config, report_paths=report_paths)
    if args.report_suppressions:
        for site in report.suppression_sites:
            print(site.format())
        n = len(report.suppression_sites)
        stale = sum(1 for s in report.suppression_sites if not s.used)
        print(f"{n} suppression site(s), {stale} stale")
        return 0
    if args.output_format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_json_dict() for f in report.findings],
                    "suppressed": [f.to_json_dict() for f in report.suppressed],
                    "files_scanned": report.files_scanned,
                    "duration_s": round(report.duration_s, 3),
                },
                indent=2,
            )
        )
    elif args.output_format == "sarif":
        print(json.dumps(to_sarif(report), indent=2))
    else:
        for f in report.findings:
            print(f.format())
        if args.show_suppressed:
            for f in report.suppressed:
                print(f"{f.format()}  (suppressed)")
        print(report.summary())
    failed = bool(report.errors) or (args.strict and report.warnings)
    return 1 if failed else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="lint",
        description="TPU-aware static analyzer for predictionio_tpu code "
        "(tracer safety, recompile hazards, host-sync stalls, reachability-"
        "scoped serving/train rules, mesh/sharding agreement, async-blocking "
        "calls, concurrency, storage contracts)",
    )
    add_lint_arguments(parser)
    return run_lint(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
