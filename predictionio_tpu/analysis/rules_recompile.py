"""Recompile-hazard rules.

XLA compilation is cached on (pytree structure, shapes, dtypes, static-arg
values). Three idioms silently defeat the cache and turn the serving hot
path into a compile loop:

- passing Python literals (bools, strings, lists/dicts) to a jitted
  function at positions not declared static — strings aren't pytree leaves,
  and structure-varying containers retrace per shape;
- building the jit wrapper itself inside a loop (``jax.jit(f)`` per
  request) — a fresh wrapper means a fresh cache;
- a jitted closure capturing mutable enclosing state — the first trace
  bakes the captured value in, later mutations are silently ignored.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    register_checker,
    register_rule,
)

register_rule(
    "recompile-unhashable-arg",
    "recompile",
    Severity.WARNING,
    "literal bool/str/list/dict argument to a jitted function at a "
    "position not declared in static_argnums/static_argnames; each "
    "distinct value or structure retraces",
)
register_rule(
    "recompile-jit-in-loop",
    "recompile",
    Severity.WARNING,
    "jax.jit/pjit/shard_map wrapper constructed inside a loop; every "
    "iteration gets a fresh compilation cache",
)
register_rule(
    "recompile-closure-capture",
    "recompile",
    Severity.WARNING,
    "jitted closure captures mutable enclosing state; the first trace "
    "freezes the captured value and later mutations are ignored",
)


def _collect_jitted_defs(
    tree: ast.Module,
) -> dict[str, tuple[astutil.JitInfo, list[str] | None]]:
    """Module-level jitted defs: `@jax.jit def f` and `f = jax.jit(g, ...)`.
    Maps name -> (jit info, positional param names when the def is visible —
    needed to resolve static_argnames for positionally-passed args)."""
    defs: dict[str, list[str]] = {
        stmt.name: [p.arg for p in stmt.args.posonlyargs + stmt.args.args]
        for stmt in tree.body
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    out: dict[str, tuple[astutil.JitInfo, list[str] | None]] = {}
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = astutil.jit_decorator_info(stmt)
            if info is not None:
                out[stmt.name] = (info, defs[stmt.name])
        elif isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            info = astutil.jit_expr_info(stmt.value)
            if info is not None:
                # f = jax.jit(g): reuse g's params when g is a local def
                inner = (
                    stmt.value.args[0].id
                    if stmt.value.args
                    and isinstance(stmt.value.args[0], ast.Name)
                    else None
                )
                params = defs.get(inner) if inner else None
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        out[target.id] = (info, params)
    return out


def _literal_kind(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, bool):
        return "bool"
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return "str"
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set"
    return None


def _check_call_args(
    ctx: FileContext,
    call: ast.Call,
    name: str,
    info: astutil.JitInfo,
    params: list[str] | None,
) -> list[Finding]:
    findings = []
    for i, arg in enumerate(call.args):
        if i in info.static_argnums:
            continue
        # static_argnames covers positionally-passed args too (JAX resolves
        # names to positions); credit it when the def's params are visible
        if params and i < len(params) and params[i] in info.static_argnames:
            continue
        kind = _literal_kind(arg)
        if kind:
            findings.append(
                ctx.finding(
                    "recompile-unhashable-arg",
                    arg,
                    f"{kind} literal passed to jitted {name!r} at position "
                    f"{i} not in static_argnums; declare it static or hoist "
                    f"it out of the call",
                )
            )
    for kw in call.keywords:
        if kw.arg is None or kw.arg in info.static_argnames:
            continue
        kind = _literal_kind(kw.value)
        if kind:
            findings.append(
                ctx.finding(
                    "recompile-unhashable-arg",
                    kw.value,
                    f"{kind} literal passed to jitted {name!r} as "
                    f"{kw.arg}= not in static_argnames; declare it static "
                    f"or hoist it out of the call",
                )
            )
    return findings


def _check_jit_in_loops(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[int] = set()

    def flag(sub: ast.AST, info: astutil.JitInfo):
        if id(sub) in seen:
            return
        seen.add(id(sub))
        findings.append(
            ctx.finding(
                "recompile-jit-in-loop",
                sub,
                f"{info.kind} wrapper constructed inside a loop; hoist "
                f"the jitted callable out so the compilation cache "
                f"survives iterations",
            )
        )

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        # stay out of nested defs: a function *defined* in the loop body
        # runs later, not per iteration — but its jit decoration DOES
        # construct a fresh wrapper each time through the loop
        for sub in astutil.walk_skipping_nested_functions(
            node.body + node.orelse
        ):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in sub.decorator_list:
                    info = astutil.jit_expr_info(dec)
                    if info is not None:
                        flag(dec, info)
            elif isinstance(sub, ast.Call):
                info = astutil.jit_expr_info(sub)
                if info is not None:
                    flag(sub, info)
    return findings


def _mutable_locals(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    """Names the enclosing function binds to mutable containers or mutates."""
    out: set[str] = set()
    for node in astutil.walk_skipping_nested_functions(fn.body):
        if isinstance(node, ast.Assign):
            if astutil.is_mutable_literal(node.value) or (
                astutil.is_mutable_factory_call(node.value)
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
            out.add(node.target.id)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in astutil.MUTATING_METHODS and isinstance(
                node.func.value, ast.Name
            ):
                out.add(node.func.value.id)
    return out


def _free_reads(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    bound = set(astutil.param_names(fn))
    reads: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                bound.add(node.id)
            else:
                reads.add(node.id)
    return reads - bound


def _check_closure_capture(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for outer in ast.walk(ctx.tree):
        if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mutable = _mutable_locals(outer)
        if not mutable:
            continue
        for inner in ast.walk(outer):
            if inner is outer or not isinstance(
                inner, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if astutil.jit_decorator_info(inner) is None:
                continue
            hits = _free_reads(inner) & mutable
            if hits:
                findings.append(
                    ctx.finding(
                        "recompile-closure-capture",
                        inner,
                        f"jitted {inner.name!r} captures mutable enclosing "
                        f"state {'/'.join(sorted(hits))!r}; pass it as an "
                        f"argument (traced or static) instead",
                    )
                )
    return findings


@register_checker
def check_recompile_hazards(ctx: FileContext):
    # every hazard here needs a jit/pjit/shard_map wrapper somewhere in the
    # file; the substring test skips the three tree walks for the ~90% of
    # files that have none (lint wall-clock budget)
    if "jit" not in ctx.source and "shard_map" not in ctx.source:
        return []
    findings: list[Finding] = []
    jitted = _collect_jitted_defs(ctx.tree)
    if jitted:
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted
            ):
                info, params = jitted[node.func.id]
                findings.extend(
                    _check_call_args(ctx, node, node.func.id, info, params)
                )
    findings.extend(_check_jit_in_loops(ctx))
    findings.extend(_check_closure_capture(ctx))
    return findings
