"""Analyzer engine: rule registry, call-graph build, suppression, reporting.

Rules live in sibling ``rules_*`` modules; each declares its metadata with
:func:`register_rule` and registers one checker callable with
:func:`register_checker`. A checker receives a :class:`FileContext` and
yields :class:`Finding` objects; the engine applies inline/file suppressions
afterwards so checkers never need to know about them.

Since ISSUE 16 the engine is whole-program: :func:`analyze_paths` first
parses every file, builds one cross-file call graph (callgraph.py) and the
per-category reachability sets (reachability.py) from the config's declared
``entry_points``, then runs the per-file checkers against that shared state.
Context-sensitive rules ask "is this function reachable from a serving /
predict / train / eval / async entry point" instead of matching hand-kept
glob + function-name lists.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import fnmatch
import io
import os
import re
import time
import tokenize
from typing import Callable, Iterable, Iterator

from .callgraph import ProjectGraph, build_project
from .reachability import (
    CATEGORY_ASYNC,
    CATEGORY_EVAL,
    CATEGORY_PREDICT,
    CATEGORY_SERVING,
    CATEGORY_TRAIN,
    EntryPoint,
    Reachability,
)


class Severity(enum.IntEnum):
    INFO = 10
    WARNING = 20
    ERROR = 30


@dataclasses.dataclass(frozen=True)
class RuleMeta:
    """Declared identity of one rule: id, family, default severity, docs."""

    id: str
    family: str
    severity: Severity
    summary: str


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: Severity
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity.name.lower()} [{self.rule}] {self.message}"
        )

    def to_json_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity.name.lower(),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


# ---------------------------------------------------------------------------
# entry-point declarations (what the old glob/function-name lists became)
# ---------------------------------------------------------------------------

# request hot path: every def in these modules serves traffic (aiohttp
# handlers + their helpers); anything they reach inherits the category
_SERVING_ENTRY_GLOBS = (
    "*/controller/serving.py",
    "*/workflow/create_server.py",
    "*/data/api/*.py",
    # bandit accounting rides the query hot path (record_impression) and
    # the rollout heartbeat — same obs + host-sync discipline as serving.
    # (models/sequential is NOT a serving root: its engine is covered by
    # the predict-category roots below, whose rules know the sanctioned
    # ops/topk endings.)
    "*/bandit/*.py",
)

# module-scoped obs rules (print-logging / label cardinality) are not
# reachability-based: they cover the request-path modules plus the new
# engine + instrument modules that export pio_* families
_OBS_MODULE_GLOBS = _SERVING_ENTRY_GLOBS + ("*/models/sequential/*.py",)

# the predict path's named roots: Engine.dispatch_batch / the batchpredict
# drain / ann search / eval-grid scoring. Reachability covers the helpers
# these flow through — the names stay ONLY for the roots themselves.
_PREDICT_ENTRY_GLOBS = (
    "*/models/*/engine.py",
    "*/ann/*.py",
    "*/workflow/batch_predict.py",
    "*/controller/engine.py",
    "*/tuning/*.py",
)
_PREDICT_ENTRY_FUNCTIONS = (
    "predict",
    "predict_batch",
    "predict_batch_dispatch",
    "predict_with_context",
    "batch_predict",
    "serve",
    "search_async",
    "fetch",
    "record_recall",
    "dispatch_batch",
    "run_pipeline",
    "dispatch_scores",
    "score_cell",
)

# training loops: bare device->host syncs anywhere these reach must go
# through timed_block_until_ready / obs.xray device accounting
_TRAIN_ENTRY_GLOBS = (
    "*/ops/als.py",
    "*/ops/als_sharded.py",
    "*/ops/spd_solve.py",
    "*/stream/trainers.py",
    "*/stream/pipeline.py",
    "*/tuning/*.py",
    # the lifecycle controller's tick path reaches the grid runner and
    # registry — bare device syncs there ride the same accounting rule
    "*/lifecycle/*.py",
)

# evaluation grid: held-out scoring must ride Engine.dispatch_batch's
# mega-batches — a per-query .predict() loop anywhere the cell scorers
# reach reinstates one device round-trip per held-out query per cell
_EVAL_ENTRY_FUNCTIONS = ("dispatch_scores", "score_cell")

# fleet event loops: every async def in these modules runs on an event
# loop whose stall is a fleet-wide p99 spike
_ASYNC_ENTRY_GLOBS = (
    "*/fleet/*.py",
    "*/data/api/*.py",
    "*/workflow/create_server.py",
    # the profiling plane (ISSUE 18): capture/publish do real file I/O and
    # the sampler walks every thread's frames — any async def that grows
    # here (or any handler that calls into them without an executor hop)
    # must prove its blocking work runs off the event loop
    "*/obs/profiler.py",
    "*/obs/sampler.py",
    # the lifecycle controller's async run() shares the fleet parent's
    # event loop with the gateway — its ticks must stay on the executor
    "*/lifecycle/*.py",
)

DEFAULT_ENTRY_POINTS: tuple[EntryPoint, ...] = (
    tuple(EntryPoint(CATEGORY_SERVING, g) for g in _SERVING_ENTRY_GLOBS)
    + tuple(
        EntryPoint(CATEGORY_PREDICT, g, f)
        for g in _PREDICT_ENTRY_GLOBS
        for f in _PREDICT_ENTRY_FUNCTIONS
    )
    + tuple(EntryPoint(CATEGORY_TRAIN, g) for g in _TRAIN_ENTRY_GLOBS)
    + tuple(
        EntryPoint(CATEGORY_EVAL, "*/tuning/*.py", f)
        for f in _EVAL_ENTRY_FUNCTIONS
    )
    + tuple(
        EntryPoint(CATEGORY_ASYNC, g, async_only=True)
        for g in _ASYNC_ENTRY_GLOBS
    )
)


@dataclasses.dataclass(frozen=True)
class LintConfig:
    """Tunables a caller (CLI, tests, CI) may override."""

    # declared reachability roots — the ONLY place serving/predict/train/
    # eval/async scopes are configured (the per-rule glob+name lists these
    # replaced are gone; helpers are covered by the call graph)
    entry_points: tuple[EntryPoint, ...] = DEFAULT_ENTRY_POINTS
    # modules on the request hot path, used by the module-scoped obs rules
    # (print-logging / label cardinality) which are not reachability-based
    serving_globs: tuple[str, ...] = _OBS_MODULE_GLOBS
    # modules on the stream (speed-layer) path: event-store reads here
    # must be bounded (rule stream-unbounded-drain). The bandit reward
    # tail and the sequential engine's ordered-event pager drain the same
    # store from long-lived loops, so they ride the same rule.
    stream_globs: tuple[str, ...] = (
        "*/stream/*.py",
        "*/bandit/*.py",
        "*/models/sequential/*.py",
    )
    # fleet gateway/supervisor modules: outbound replica calls and
    # replica state transitions must route through the span/telemetry
    # helpers (rule fleet-unattributed-proxy) — an unattributed proxy is
    # a hop /traces/recent can never assemble, an unattributed
    # eject/park is evidence the incident recorder never sees
    fleet_globs: tuple[str, ...] = (
        "*/fleet/gateway.py",
        "*/fleet/supervisor.py",
        "*/fleet/launch.py",
        "*/fleet/autoscaler.py",
        "*/fleet/hostrt.py",
    )
    # modules holding sharded kernels: the mesh-* family guards axis-name
    # agreement and single-host materialization here
    mesh_sharded_globs: tuple[str, ...] = (
        "*/parallel/*.py",
        "*/ops/*_sharded.py",
    )
    # modules whose event loops must never block (rule async-blocking-call
    # reports at call sites inside these files)
    async_globs: tuple[str, ...] = _ASYNC_ENTRY_GLOBS
    # rule ids to run; None = all registered
    enabled: frozenset[str] | None = None


@dataclasses.dataclass
class ProjectState:
    """Whole-program state shared by every checker in a run."""

    graph: ProjectGraph
    reach: Reachability


@dataclasses.dataclass
class FileContext:
    """Everything a checker may look at for one file."""

    path: str  # absolute path on disk ('' for in-memory sources)
    display_path: str  # what findings print; also the call-graph file key
    source: str
    tree: ast.Module
    config: LintConfig
    cache: dict  # shared across the whole run (cross-file state)

    def finding(self, rule_id: str, node: ast.AST | int, message: str) -> Finding:
        meta = _RULES[rule_id]
        if isinstance(node, int):
            line, col = node, 0
        else:
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
        return Finding(rule_id, meta.severity, self.display_path, line, col, message)

    @property
    def graph_path(self) -> str:
        """The key this file is indexed under in the call graph: the
        absolute path when we have one (display paths are cwd-relative and
        would stop matching globs when linting from inside the tree)."""
        return self.path or self.display_path

    def project(self) -> ProjectState:
        """The run's whole-program state. ``analyze_paths`` pre-builds it
        over every scanned file; a bare ``analyze_source`` (snippet tests)
        gets a single-file graph so in-file reachability still works."""
        state = self.cache.get("project_state")
        if isinstance(state, ProjectState):
            if state.graph.has_file(self.graph_path):
                return state
        per = self.cache.setdefault("_single_file_states", {})
        if self.graph_path not in per:
            graph = build_project([(self.graph_path, self.tree)])
            per[self.graph_path] = ProjectState(
                graph, Reachability(graph, self.config.entry_points)
            )
        return per[self.graph_path]


Checker = Callable[[FileContext], Iterable[Finding]]

_RULES: dict[str, RuleMeta] = {}
_CHECKERS: list[Checker] = []


def register_rule(
    rule_id: str, family: str, severity: Severity, summary: str
) -> RuleMeta:
    if rule_id in _RULES:
        raise ValueError(f"duplicate rule id {rule_id!r}")
    meta = RuleMeta(rule_id, family, severity, summary)
    _RULES[rule_id] = meta
    return meta


def register_checker(fn: Checker) -> Checker:
    _CHECKERS.append(fn)
    return fn


def all_rules() -> list[RuleMeta]:
    return sorted(_RULES.values(), key=lambda m: (m.family, m.id))


# registered eagerly so FileContext.finding works for parse failures too
register_rule(
    "parse-error",
    "engine",
    Severity.ERROR,
    "file does not parse as Python; nothing else can be checked",
)

register_rule(
    "suppression-stale",
    "engine",
    Severity.WARNING,
    "a # pio-lint: disable comment whose target no longer produces that "
    "finding — delete it or re-justify it",
)


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*pio-lint:\s*disable(?P<file>-file)?(?:=(?P<rules>[A-Za-z0-9_\-, ]+))?"
)


@dataclasses.dataclass(frozen=True)
class SuppressionSite:
    """One ``# pio-lint: disable`` comment, for the suppression inventory
    (``pio lint --report-suppressions``) and stale detection."""

    path: str
    line: int
    rules: tuple[str, ...] | None  # None = blanket (all rules)
    reason: str
    file_level: bool
    targets: tuple[int, ...]  # lines the comment covers (file_level: ())
    used: bool = False  # did any raw finding match it this run?

    def format(self) -> str:
        ids = ",".join(self.rules) if self.rules else "ALL"
        scope = "file" if self.file_level else f"line {self.line}"
        state = "used" if self.used else "STALE"
        reason = self.reason or "(no reason given)"
        return f"{self.path}:{self.line}: [{ids}] {scope} {state} — {reason}"


def _iter_comment_tokens(source: str) -> Iterator[tuple[int, int, str]]:
    """(lineno, col, text) for every real COMMENT token. Tokenizing (vs a
    per-line regex) keeps ``# pio-lint:`` examples inside docstrings from
    registering as suppression sites."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # unparseable tail: the parse-error finding covers it


def _parse_suppression_sites(source: str, path: str) -> list[SuppressionSite]:
    """Every suppression comment in the file. A comment alone on a line
    also covers the next line, so decorators/long calls can be annotated
    above."""
    if "pio-lint" not in source:  # skip tokenizing the common case
        return []
    sites: list[SuppressionSite] = []
    lines = source.splitlines()
    for lineno, col, text in _iter_comment_tokens(source):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        if rules is not None:
            # anything after `--` is the human reason, not an id (the id
            # character class overlaps it, so cut there first)
            rules = rules.split("--", 1)[0]
        # the full reason, from the original text (ids can't contain "--")
        _, sep, reason = text[m.start():].partition("--")
        reason = reason.strip() if sep else ""
        ids = tuple(
            r.strip() for r in (rules or "").split(",") if r.strip()
        ) or None
        file_level = bool(m.group("file"))
        standalone = lines[lineno - 1][:col].strip() == ""
        if file_level:
            targets: tuple[int, ...] = ()
        elif standalone:
            targets = (lineno, lineno + 1)  # standalone covers next line
        else:
            targets = (lineno,)
        sites.append(
            SuppressionSite(
                path=path,
                line=lineno,
                rules=ids,
                reason=reason,
                file_level=file_level,
                targets=targets,
            )
        )
    return sites


def _suppression_maps(
    sites: Iterable[SuppressionSite],
) -> tuple[dict[int, frozenset[str] | None], frozenset[str] | None, bool]:
    """Collapse sites into the per-line / file-level lookup maps."""
    per_line: dict[int, frozenset[str] | None] = {}
    file_rules: set[str] = set()
    file_all = False
    for site in sites:
        ids = frozenset(site.rules) if site.rules is not None else None
        if site.file_level:
            if ids is None:
                file_all = True
            else:
                file_rules.update(ids)
            continue
        for t in site.targets:
            prev = per_line.get(t, frozenset())
            if prev is None or ids is None:
                per_line[t] = None
            else:
                per_line[t] = prev | ids
    return per_line, frozenset(file_rules) or None, file_all


def _is_suppressed(
    f: Finding,
    per_line: dict[int, frozenset[str] | None],
    file_rules: frozenset[str] | None,
    file_all: bool,
) -> bool:
    if file_all or (file_rules and f.rule in file_rules):
        return True
    ids = per_line.get(f.line, frozenset())
    return ids is None or f.rule in ids


def _mark_usage(
    sites: list[SuppressionSite], raw: list[Finding]
) -> list[SuppressionSite]:
    """Which suppression sites matched at least one raw finding."""
    out = []
    for site in sites:
        if site.file_level:
            used = any(
                site.rules is None or f.rule in site.rules for f in raw
            )
        else:
            used = any(
                f.line in site.targets
                and (site.rules is None or f.rule in site.rules)
                for f in raw
            )
        out.append(dataclasses.replace(site, used=used))
    return out


# ---------------------------------------------------------------------------
# analysis drivers
# ---------------------------------------------------------------------------


def _parse_error_finding(display_path: str, exc: SyntaxError) -> Finding:
    meta = _RULES["parse-error"]
    return Finding(
        meta.id,
        meta.severity,
        display_path,
        exc.lineno or 1,
        (exc.offset or 1) - 1,
        f"syntax error: {exc.msg}",
    )


def _analyze_tree(
    source: str,
    display_path: str,
    tree: ast.Module,
    config: LintConfig,
    cache: dict,
    path: str,
) -> tuple[list[Finding], list[Finding], list[SuppressionSite]]:
    """Run every checker over one pre-parsed file, then apply suppressions
    and stale-suppression detection."""
    ctx = FileContext(path, display_path, source, tree, config, cache)
    raw: list[Finding] = []
    for checker in _CHECKERS:
        for f in checker(ctx):
            if config.enabled is not None and f.rule not in config.enabled:
                continue
            raw.append(f)
    sites = _parse_suppression_sites(source, display_path)
    sites = _mark_usage(sites, raw)
    # stale detection only audits full runs: under --rule filtering most
    # suppressions legitimately match nothing
    if config.enabled is None:
        meta = _RULES["suppression-stale"]
        for site in sites:
            if site.used or site.rules is None:
                continue  # blanket disables can't be stale-checked
            if "suppression-stale" in site.rules:
                # a meta-suppression's own finding only exists after this
                # pass; auditing it here would always call it stale
                continue
            ids = ",".join(site.rules)
            raw.append(
                Finding(
                    meta.id,
                    meta.severity,
                    display_path,
                    site.line,
                    0,
                    f"suppression [{ids}] no longer matches any finding "
                    "on its target line(s); delete it or re-justify it",
                )
            )
    per_line, file_rules, file_all = _suppression_maps(sites)
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.rule)):
        if _is_suppressed(f, per_line, file_rules, file_all):
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed, sites


def analyze_source(
    source: str,
    display_path: str,
    config: LintConfig | None = None,
    cache: dict | None = None,
    path: str = "",
) -> tuple[list[Finding], list[Finding]]:
    """Analyze one source blob. Returns ``(active, suppressed)`` findings.

    Without a pre-built project in ``cache`` the call graph covers just
    this file — cross-file edges need :func:`analyze_paths`.
    """
    config = config or LintConfig()
    cache = cache if cache is not None else {}
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [_parse_error_finding(display_path, exc)], []
    active, suppressed, _sites = _analyze_tree(
        source, display_path, tree, config, cache, path
    )
    return active, suppressed


@dataclasses.dataclass
class Report:
    findings: list[Finding]
    suppressed: list[Finding]
    files_scanned: int
    duration_s: float
    suppression_sites: list[SuppressionSite] = dataclasses.field(
        default_factory=list
    )

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity >= Severity.ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    def summary(self) -> str:
        return (
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s) "
            f"({len(self.suppressed)} suppressed) in {self.files_scanned} "
            f"file(s) [{self.duration_s * 1000:.0f} ms]"
        )


_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    seen: set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and os.path.abspath(p) not in seen:
                seen.add(os.path.abspath(p))
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    full = os.path.join(dirpath, name)
                    if os.path.abspath(full) not in seen:
                        seen.add(os.path.abspath(full))
                        yield full


def analyze_paths(
    paths: Iterable[str],
    config: LintConfig | None = None,
    report_paths: Iterable[str] | None = None,
) -> Report:
    """Whole-program run: parse everything, build the call graph once,
    then check each file against the shared reachability state.

    ``report_paths`` (absolute paths) limits which files' findings are
    REPORTED — the graph is still built over all of them, so --changed
    keeps whole-program context.
    """
    config = config or LintConfig()
    start = time.monotonic()
    cwd = os.getcwd()
    report_set = (
        {os.path.abspath(p) for p in report_paths}
        if report_paths is not None
        else None
    )
    files: list[tuple[str, str, str, ast.Module | None, SyntaxError | None]] = []
    for file_path in iter_python_files(paths):
        abs_path = os.path.abspath(file_path)
        display = os.path.relpath(abs_path, cwd)
        if display.startswith(".." + os.sep):
            display = abs_path
        try:
            with open(abs_path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError:
            continue
        try:
            tree: ast.Module | None = ast.parse(source)
            err: SyntaxError | None = None
        except SyntaxError as exc:
            tree, err = None, exc
        files.append((abs_path, display, source, tree, err))

    # graph keys are ABSOLUTE paths: display paths are cwd-relative and
    # would silently stop matching entry-point globs when linting from
    # inside the package tree
    graph = build_project(
        (abs_path, tree) for abs_path, _, _, tree, _ in files if tree is not None
    )
    cache: dict = {
        "project_state": ProjectState(
            graph, Reachability(graph, config.entry_points)
        )
    }

    findings: list[Finding] = []
    suppressed: list[Finding] = []
    sites: list[SuppressionSite] = []
    for abs_path, display, source, tree, err in files:
        if report_set is not None and abs_path not in report_set:
            reportable = False
        else:
            reportable = True
        if tree is None:
            if reportable and err is not None:
                findings.append(_parse_error_finding(display, err))
            continue
        active, supp, file_sites = _analyze_tree(
            source, display, tree, config, cache, abs_path
        )
        if reportable:
            findings.extend(active)
            suppressed.extend(supp)
            sites.extend(file_sites)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(
        findings, suppressed, len(files), time.monotonic() - start, sites
    )


def matches_any_glob(display_path: str, globs: Iterable[str]) -> bool:
    """Match a path against config globs, OS-separator agnostic."""
    norm = display_path.replace(os.sep, "/")
    return any(fnmatch.fnmatch(norm, g) for g in globs)
