"""Analyzer engine: rule registry, suppression, file walking, reporting.

Rules live in sibling ``rules_*`` modules; each declares its metadata with
:func:`register_rule` and registers one checker callable with
:func:`register_checker`. A checker receives a :class:`FileContext` and
yields :class:`Finding` objects; the engine applies inline/file suppressions
afterwards so checkers never need to know about them.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import fnmatch
import os
import re
import time
from typing import Callable, Iterable, Iterator


class Severity(enum.IntEnum):
    INFO = 10
    WARNING = 20
    ERROR = 30


@dataclasses.dataclass(frozen=True)
class RuleMeta:
    """Declared identity of one rule: id, family, default severity, docs."""

    id: str
    family: str
    severity: Severity
    summary: str


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: Severity
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity.name.lower()} [{self.rule}] {self.message}"
        )

    def to_json_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity.name.lower(),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclasses.dataclass(frozen=True)
class LintConfig:
    """Tunables a caller (CLI, tests, CI) may override."""

    # modules on the request hot path: host syncs here stall the event loop
    serving_globs: tuple[str, ...] = (
        "*/controller/serving.py",
        "*/workflow/create_server.py",
        "*/data/api/*.py",
    )
    # function names allowed to host-sync on the serving path (startup /
    # shutdown hooks that run outside the request loop)
    hostsync_allow_functions: tuple[str, ...] = ()
    # modules on the stream (speed-layer) path: event-store reads here
    # must be bounded (rule stream-unbounded-drain)
    stream_globs: tuple[str, ...] = ("*/stream/*.py",)
    # modules containing training loops: bare device->host syncs here must
    # go through timed_block_until_ready / obs.xray device accounting so
    # device time can't leak out of the train profile (rule
    # train-unaccounted-sync)
    train_globs: tuple[str, ...] = (
        "*/ops/als.py",
        "*/ops/als_sharded.py",
        "*/ops/spd_solve.py",
        "*/stream/trainers.py",
        "*/stream/pipeline.py",
        # the evaluation grid trains one model per fold×params cell under
        # a per-cell xray profile — a bare sync in the cell loop leaks
        # device time out of every cell's training evidence at once
        "*/tuning/*.py",
    )
    # fleet gateway/supervisor modules: outbound replica calls and
    # replica state transitions must route through the span/telemetry
    # helpers (rule fleet-unattributed-proxy) — an unattributed proxy is
    # a hop /traces/recent can never assemble, an unattributed
    # eject/park is evidence the incident recorder never sees
    fleet_globs: tuple[str, ...] = (
        "*/fleet/gateway.py",
        "*/fleet/supervisor.py",
        "*/fleet/launch.py",
        # the autoscaler's scaling actions are replica-set transitions:
        # each must ride the span/metric attribution funnel so the
        # scale-out/scale-in timeline is replayable from telemetry
        "*/fleet/autoscaler.py",
    )
    # engine modules whose predict paths must keep score+select fused on
    # device (rule serving-host-roundtrip): a full-array device fetch or a
    # host argsort there ships O(corpus) floats over the wire per query
    # instead of O(k) through the fused helper (ops/topk). The ann/
    # package is in scope too: the index search paths exist precisely to
    # keep the fetch O(batch*k), so a host argsort or full-array fetch
    # growing there would defeat the subsystem silently
    serving_predict_globs: tuple[str, ...] = (
        "*/models/*/engine.py",
        "*/ann/*.py",
        # the offline mega-batch path (pio batchpredict): its dispatch /
        # drain loop feeds the same fused kernels at device-saturating
        # batch sizes, where a per-item device_get or host argsort
        # sneaking back in costs O(mega-batch * corpus), not O(batch * k)
        "*/workflow/batch_predict.py",
        "*/controller/engine.py",
        # the evaluation grid's cell scoring rides the same mega-batch
        # entry (tuning/cells.dispatch_scores -> Engine.dispatch_batch);
        # a host round-trip here multiplies by cells × held-out queries
        "*/tuning/*.py",
    )
    # function names that make up the predict path inside those modules
    # (nested helpers like a dispatch's `finalize` are covered implicitly)
    serving_predict_functions: tuple[str, ...] = (
        "predict",
        "predict_batch",
        "predict_batch_dispatch",
        "predict_with_context",
        "batch_predict",
        "serve",
        # the ann search path (ann/search.py, ann/lifecycle.py)
        "search_async",
        "fetch",
        "record_recall",
        # the offline mega-batch path (Engine.dispatch_batch and the
        # batchpredict pipeline's scheduler/drain loop — nested helpers
        # like `finalize`/`drain` are covered implicitly)
        "dispatch_batch",
        "run_pipeline",
        # the evaluation grid's scoring path (tuning/cells.py)
        "dispatch_scores",
        "score_cell",
    )
    # evaluation-grid modules + the functions that make up the cell
    # scoring path (rule eval-per-query-predict): held-out scoring must
    # go through Engine.dispatch_batch's mega-batches — a per-query
    # ``.predict()`` loop reinstates one device round-trip per held-out
    # query per cell, the exact cost the grid exists to delete
    tuning_globs: tuple[str, ...] = ("*/tuning/*.py",)
    eval_scoring_functions: tuple[str, ...] = (
        "dispatch_scores",
        "score_cell",
    )
    # rule ids to run; None = all registered
    enabled: frozenset[str] | None = None


@dataclasses.dataclass
class FileContext:
    """Everything a checker may look at for one file."""

    path: str  # absolute path on disk ('' for in-memory sources)
    display_path: str  # what findings print
    source: str
    tree: ast.Module
    config: LintConfig
    cache: dict  # shared across the whole run (cross-file state)

    def finding(self, rule_id: str, node: ast.AST | int, message: str) -> Finding:
        meta = _RULES[rule_id]
        if isinstance(node, int):
            line, col = node, 0
        else:
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
        return Finding(rule_id, meta.severity, self.display_path, line, col, message)


Checker = Callable[[FileContext], Iterable[Finding]]

_RULES: dict[str, RuleMeta] = {}
_CHECKERS: list[Checker] = []


def register_rule(
    rule_id: str, family: str, severity: Severity, summary: str
) -> RuleMeta:
    if rule_id in _RULES:
        raise ValueError(f"duplicate rule id {rule_id!r}")
    meta = RuleMeta(rule_id, family, severity, summary)
    _RULES[rule_id] = meta
    return meta


def register_checker(fn: Checker) -> Checker:
    _CHECKERS.append(fn)
    return fn


def all_rules() -> list[RuleMeta]:
    return sorted(_RULES.values(), key=lambda m: (m.family, m.id))


# registered eagerly so FileContext.finding works for parse failures too
register_rule(
    "parse-error",
    "engine",
    Severity.ERROR,
    "file does not parse as Python; nothing else can be checked",
)


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*pio-lint:\s*disable(?P<file>-file)?(?:=(?P<rules>[A-Za-z0-9_\-, ]+))?"
)


def _parse_suppressions(
    source: str,
) -> tuple[dict[int, frozenset[str] | None], frozenset[str] | None, bool]:
    """Map line -> suppressed rule ids (None = all rules) plus file-level
    suppressions. A suppression comment alone on a line also covers the next
    line, so decorators/long calls can be annotated above.

    Returns ``(per_line, file_rules, file_all)``.
    """
    per_line: dict[int, frozenset[str] | None] = {}
    file_rules: set[str] = set()
    file_all = False
    for lineno, text in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        if rules is not None:
            # anything after `--` is the required human reason, not an id
            rules = rules.split("--", 1)[0]
        ids = (
            frozenset(r.strip() for r in rules.split(",") if r.strip())
            if rules
            else None
        )
        if m.group("file"):
            if ids is None:
                file_all = True
            else:
                file_rules.update(ids)
            continue
        targets = [lineno]
        if text[: m.start()].strip() == "":
            targets.append(lineno + 1)  # standalone comment covers next line
        for t in targets:
            prev = per_line.get(t, frozenset())
            if prev is None or ids is None:
                per_line[t] = None
            else:
                per_line[t] = prev | ids
    return per_line, frozenset(file_rules) or None, file_all


def _is_suppressed(
    f: Finding,
    per_line: dict[int, frozenset[str] | None],
    file_rules: frozenset[str] | None,
    file_all: bool,
) -> bool:
    if file_all or (file_rules and f.rule in file_rules):
        return True
    ids = per_line.get(f.line, frozenset())
    return ids is None or f.rule in ids


# ---------------------------------------------------------------------------
# analysis drivers
# ---------------------------------------------------------------------------


def analyze_source(
    source: str,
    display_path: str,
    config: LintConfig | None = None,
    cache: dict | None = None,
    path: str = "",
) -> tuple[list[Finding], list[Finding]]:
    """Analyze one source blob. Returns ``(active, suppressed)`` findings."""
    config = config or LintConfig()
    cache = cache if cache is not None else {}
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        meta = _RULES["parse-error"]
        f = Finding(
            meta.id,
            meta.severity,
            display_path,
            exc.lineno or 1,
            (exc.offset or 1) - 1,
            f"syntax error: {exc.msg}",
        )
        return [f], []
    ctx = FileContext(path, display_path, source, tree, config, cache)
    raw: list[Finding] = []
    for checker in _CHECKERS:
        for f in checker(ctx):
            if config.enabled is not None and f.rule not in config.enabled:
                continue
            raw.append(f)
    per_line, file_rules, file_all = _parse_suppressions(source)
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.rule)):
        if _is_suppressed(f, per_line, file_rules, file_all):
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed


@dataclasses.dataclass
class Report:
    findings: list[Finding]
    suppressed: list[Finding]
    files_scanned: int
    duration_s: float

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity >= Severity.ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    def summary(self) -> str:
        return (
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s) "
            f"({len(self.suppressed)} suppressed) in {self.files_scanned} "
            f"file(s) [{self.duration_s * 1000:.0f} ms]"
        )


_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def analyze_paths(
    paths: Iterable[str], config: LintConfig | None = None
) -> Report:
    config = config or LintConfig()
    cache: dict = {}
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    count = 0
    start = time.monotonic()
    cwd = os.getcwd()
    for file_path in iter_python_files(paths):
        abs_path = os.path.abspath(file_path)
        display = os.path.relpath(abs_path, cwd)
        if display.startswith(".." + os.sep):
            display = abs_path
        try:
            with open(abs_path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError:
            continue
        count += 1
        active, supp = analyze_source(
            source, display, config=config, cache=cache, path=abs_path
        )
        findings.extend(active)
        suppressed.extend(supp)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(findings, suppressed, count, time.monotonic() - start)


def matches_any_glob(display_path: str, globs: Iterable[str]) -> bool:
    """Match a path against config globs, OS-separator agnostic."""
    norm = display_path.replace(os.sep, "/")
    return any(fnmatch.fnmatch(norm, g) for g in globs)
