"""Mesh/sharding rules: the bug classes that pass every CPU unit test and
only detonate on a pod.

Four rules, all conservative — they fire only on *literal* axis names and
provably-sharded values, because a false positive here would train people
to suppress the family before the pod-scale code even lands:

- ``mesh-unknown-axis``: a literal axis name in a ``PartitionSpec``/``P``
  does not exist on any mesh the project constructs. XLA raises this at
  runtime on the pod; the linter raises it on the laptop.
- ``mesh-collective-axis``: a literal axis name passed to a ``lax``
  collective (``psum``/``pmean``/``all_gather``/…) that no mesh declares —
  the collective would fail to find the mapped axis inside ``shard_map``.
- ``mesh-host-materialize``: ``jax.device_get`` / one-arg ``np.asarray``
  of a value produced by a sharded call inside ``parallel/`` or
  ``ops/*_sharded.py``. On a multi-host mesh a single-host materialization
  either crashes (non-addressable shards) or silently gathers the world to
  host 0. The sanctioned fetch is ``multihost_utils.process_allgather`` +
  ``obs.xray.device_fetch``.
- ``mesh-topk-unmerged``: a per-shard ``lax.top_k`` in a sharded module
  whose enclosing top-level function never routes results through the
  ``ops/topk`` pack format (``pack_batch``/``host_top_k``/…): per-shard
  winners that never merge are silently wrong answers, not errors.

Axis names are *declared* by literal ``Mesh(devs, ("data",…))`` /
``MeshSpec(…)`` constructions, ``MeshSpec.parse("data=8,model=2")`` /
``make_mesh("…")`` spec strings, ``axis="data"``-style parameter defaults,
and ``AXIS = "data"`` constants — collected over the whole project, so a
kernel file using ``P("model")`` is fine as long as ANY module constructs a
mesh with a ``model`` axis. When the project declares no axis names at all,
the axis rules stay silent (nothing to check against).
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectState,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)

register_rule(
    "mesh-unknown-axis",
    "mesh",
    Severity.ERROR,
    "PartitionSpec names a mesh axis no Mesh/MeshSpec in the project "
    "declares; in_specs/out_specs axis names must exist on the "
    "constructing mesh",
)

register_rule(
    "mesh-collective-axis",
    "mesh",
    Severity.ERROR,
    "lax collective (psum/pmean/all_gather/...) names a mesh axis no "
    "Mesh/MeshSpec in the project declares; the axis name must match a "
    "mapped mesh axis",
)

register_rule(
    "mesh-host-materialize",
    "mesh",
    Severity.ERROR,
    "jax.device_get / one-arg np.asarray of a sharded value in a sharded "
    "module single-host-materializes a global array; fetch through "
    "multihost_utils.process_allgather + obs.xray.device_fetch",
)

register_rule(
    "mesh-topk-unmerged",
    "mesh",
    Severity.ERROR,
    "per-shard lax.top_k whose results never merge through the ops/topk "
    "pack format (pack_batch/host_top_k); per-shard winners are not "
    "global winners",
)


_SPEC_NAMES = frozenset({"PartitionSpec", "P"})
_MESH_NAMES = frozenset({"Mesh"})
_MESHSPEC_NAMES = frozenset({"MeshSpec"})
_SPEC_STRING_FNS = frozenset({"make_mesh", "parse"})
# collective -> positional index of axis_name in its signature
_COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "psum_scatter": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "ppermute": 1,
    "pbroadcast": 1,
    "axis_index": 0,
}
_AXIS_PARAM_NAMES = frozenset({"axis", "axis_name", "axis_names", "axes"})
_TOPK_MERGE_FNS = frozenset(
    {
        "pack_batch",
        "unpack_batch",
        "fetch_topk",
        "host_top_k",
        "merge_topk",
        "topk_merge",
        "merge_shards",
    }
)


def _str_constants(node: ast.AST) -> list[tuple[str, ast.AST]]:
    return [
        (n.value, n)
        for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]


def _spec_string_axes(value: str) -> list[str]:
    """Axis names out of a "data=8,model=2" mesh-spec string."""
    out = []
    for part in value.split(","):
        name = part.partition("=")[0].strip()
        if name.isidentifier():
            out.append(name)
    return out


def _collect_declared_axes(tree: ast.Module) -> set[str]:
    """Literal axis names this file declares (see module docstring)."""
    axes: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            last = astutil.last_component(node.func)
            if last in _MESH_NAMES:
                # Mesh(devices, ("data", "model")) or axis_names= kwarg
                sources = list(node.args[1:2]) + [
                    kw.value
                    for kw in node.keywords
                    if kw.arg in ("axis_names", "names")
                ]
                for src in sources:
                    axes.update(v for v, _ in _str_constants(src))
            elif last in _MESHSPEC_NAMES:
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    axes.update(v for v, _ in _str_constants(arg))
            elif last in _SPEC_STRING_FNS:
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ):
                        axes.update(_spec_string_axes(arg.value))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            pos = list(args.posonlyargs) + list(args.args)
            for a, default in zip(pos[len(pos) - len(args.defaults):], args.defaults):
                if a.arg in _AXIS_PARAM_NAMES:
                    axes.update(v for v, _ in _str_constants(default))
            for a, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None and a.arg in _AXIS_PARAM_NAMES:
                    axes.update(v for v, _ in _str_constants(default))
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Name)
                    and "axis" in tgt.id.lower()
                    and isinstance(node.value, (ast.Constant, ast.Tuple, ast.List))
                ):
                    axes.update(v for v, _ in _str_constants(node.value))
    return axes


def _declared_axes(ctx: FileContext, state: ProjectState) -> set[str]:
    """Project-wide union of declared axis names, cached per graph."""
    if ctx.cache.get("_mesh_axes_graph") is state.graph:
        return ctx.cache["_mesh_axes"]
    axes: set[str] = set()
    for _path, tree in state.graph.file_trees():
        axes |= _collect_declared_axes(tree)
    ctx.cache["_mesh_axes"] = axes
    ctx.cache["_mesh_axes_graph"] = state.graph
    return axes


# a file can only fire the axis rules if one of these appears textually
# (P( covers `from jax.sharding import PartitionSpec as P` call sites);
# the substring gate skips the full-tree walk for files with none
_AXIS_NEEDLES = ("PartitionSpec", "P(") + tuple(_COLLECTIVES)


@register_checker
def check_mesh_axis_names(ctx: FileContext):
    """mesh-unknown-axis + mesh-collective-axis: literal axis names at use
    sites must be declared by SOME mesh construction in the project."""
    if not any(n in ctx.source for n in _AXIS_NEEDLES):
        return []
    state = ctx.project()
    declared = _declared_axes(ctx, state)
    if not declared:
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        last = astutil.last_component(node.func)
        if last in _SPEC_NAMES:
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                for value, where in _str_constants(arg):
                    if value not in declared:
                        findings.append(
                            ctx.finding(
                                "mesh-unknown-axis",
                                where,
                                f"PartitionSpec axis {value!r} is not "
                                "declared by any Mesh/MeshSpec in the "
                                f"project (declared: "
                                f"{sorted(declared)})",
                            )
                        )
        elif last in _COLLECTIVES:
            idx = _COLLECTIVES[last]
            axis_args = [
                kw.value for kw in node.keywords if kw.arg == "axis_name"
            ]
            if not axis_args and len(node.args) > idx:
                axis_args = [node.args[idx]]
            for arg in axis_args:
                # literal names only: a variable axis arg is unknowable
                if not isinstance(arg, (ast.Constant, ast.Tuple, ast.List)):
                    continue
                for value, where in _str_constants(arg):
                    if value not in declared:
                        findings.append(
                            ctx.finding(
                                "mesh-collective-axis",
                                where,
                                f"collective {last}() names axis "
                                f"{value!r}, which no Mesh/MeshSpec in "
                                "the project declares (declared: "
                                f"{sorted(declared)})",
                            )
                        )
    return findings


_SHARDED_WRAPPERS = frozenset({"shard_map", "pjit"})
_MATERIALIZE_ASARRAY = frozenset(
    {("np", "asarray"), ("numpy", "asarray"), ("onp", "asarray")}
)


def _is_sharded_producer_call(
    call: ast.Call, producer_names: frozenset[str]
) -> bool:
    """Does this call expression yield a sharded array?"""
    func = call.func
    # shard_map(f, ...)(args) / pjit(f, ...)(args)
    if isinstance(func, ast.Call):
        inner = astutil.last_component(func.func)
        if inner in _SHARDED_WRAPPERS:
            return True
    last = astutil.last_component(func)
    if last == "make_array_from_process_local_data":
        return True
    if last == "device_put" and len(call.args) >= 2:
        return True  # device_put with an explicit sharding
    if isinstance(func, ast.Name) and func.id in producer_names:
        return True
    return False


def _project_producer_names(
    ctx: FileContext, state: ProjectState
) -> frozenset[str]:
    """Top-level functions whose bodies apply shard_map/pjit — calling
    them yields sharded arrays (e.g. ``_als_sharded_step``)."""
    if ctx.cache.get("_mesh_producers_graph") is state.graph:
        return ctx.cache["_mesh_producers"]
    names: set[str] = set()
    for fn in state.graph.functions.values():
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                inner = astutil.last_component(node.func)
                if inner in _SHARDED_WRAPPERS:
                    names.add(fn.name)
                    break
    out = frozenset(names)
    ctx.cache["_mesh_producers"] = out
    ctx.cache["_mesh_producers_graph"] = state.graph
    return out


def _materialize_label(call: ast.Call) -> str | None:
    func = call.func
    d = astutil.dotted(func)
    if d:
        parts = tuple(d.split("."))
        if len(parts) >= 2:
            if parts[-2:] == ("jax", "device_get"):
                return d + "()"
            if (
                parts[-2:] in _MATERIALIZE_ASARRAY
                and len(call.args) == 1
                and not call.keywords
            ):
                return d + "()"
    elif isinstance(func, ast.Name) and func.id == "device_get":
        return "device_get()"
    return None


@register_checker
def check_mesh_host_materialize(ctx: FileContext):
    state = ctx.project()
    if not matches_any_glob(ctx.graph_path, ctx.config.mesh_sharded_globs):
        return []
    producers = _project_producer_names(ctx, state)
    findings: list[Finding] = []

    def scan(body: list[ast.stmt]) -> None:
        tainted: set[str] = set()
        nodes = list(astutil.walk_skipping_nested_functions(body))
        for node in nodes:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if _is_sharded_producer_call(node.value, producers):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            tainted.add(tgt.id)
                        elif isinstance(tgt, (ast.Tuple, ast.List)):
                            tainted.update(
                                e.id
                                for e in tgt.elts
                                if isinstance(e, ast.Name)
                            )
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            label = _materialize_label(node)
            if label is None:
                continue
            arg = node.args[0] if node.args else None
            hit = False
            if isinstance(arg, ast.Name) and arg.id in tainted:
                hit = True
            elif isinstance(arg, ast.Call) and _is_sharded_producer_call(
                arg, producers
            ):
                hit = True
            if hit:
                findings.append(
                    ctx.finding(
                        "mesh-host-materialize",
                        node,
                        f"{label} materializes a sharded array on one "
                        "host; on a multi-host mesh this crashes or "
                        "gathers the world to host 0 — fetch through "
                        "multihost_utils.process_allgather + "
                        "obs.xray.device_fetch, or keep it on device",
                    )
                )

    scan(astutil.module_level_statements(ctx.tree))
    for fn in state.graph.functions_in(ctx.graph_path):
        scan(fn.node.body)
    return findings


@register_checker
def check_mesh_topk_unmerged(ctx: FileContext):
    """Per-shard top-k in sharded modules must meet the ops/topk pack
    format somewhere in the same top-level function (the merge point)."""
    if not matches_any_glob(ctx.graph_path, ctx.config.mesh_sharded_globs):
        return []
    findings: list[Finding] = []
    for node in ctx.tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        topk_calls = []
        merges = False
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            last = astutil.last_component(sub.func)
            if last == "top_k":
                topk_calls.append(sub)
            elif last in _TOPK_MERGE_FNS:
                merges = True
        if topk_calls and not merges:
            for call in topk_calls:
                findings.append(
                    ctx.finding(
                        "mesh-topk-unmerged",
                        call,
                        "per-shard top_k result never merges through the "
                        "ops/topk pack format (pack_batch/host_top_k): "
                        "each shard's local winners are not the global "
                        "top-k — gather and re-select, or return packed "
                        "candidates",
                    )
                )
    return findings
