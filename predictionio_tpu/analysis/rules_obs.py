"""Observability rules for serving-path modules.

``print(...)`` and bare root-logger calls (``logging.info(...)`` et al.)
on the serving path are invisible to the telemetry layer: they bypass the
structured ``pio.trace`` JSON log (so a grep for a trace id misses them),
they can't be correlated with a request, and ``print`` additionally
flushes to an unbuffered fd inside the event loop. Serving code should
record spans (``predictionio_tpu.obs.tracing.Tracer.span``) or log
through a named module logger / the structured trace logger
(``predictionio_tpu.obs.tracing.get_trace_logger``).

Scope is the same ``LintConfig.serving_globs`` the host-sync family uses;
training scripts and CLIs may print freely.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)

register_rule(
    "obs-unstructured-log",
    "obs",
    Severity.WARNING,
    "print()/bare logging.* call in a serving-path module; use the "
    "structured trace logger (obs.tracing.get_trace_logger) or a span "
    "so the output joins the request's trace",
)

# direct root-logger methods: logging.info(...) etc. — a named logger
# (logging.getLogger(__name__).info) is fine and NOT matched
_ROOT_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical", "log"}
)


def _unstructured_label(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Name) and func.id == "print":
        return "print()"
    if isinstance(func, ast.Attribute) and func.attr in _ROOT_LOG_METHODS:
        d = astutil.dotted(func)
        if d and d == f"logging.{func.attr}":
            return d + "()"
    return None


@register_checker
def check_unstructured_log(ctx: FileContext):
    cfg = ctx.config
    # absolute path when available: display paths are cwd-relative and
    # would silently miss the globs when linting from inside the package
    if not matches_any_glob(ctx.path or ctx.display_path, cfg.serving_globs):
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            label = _unstructured_label(node)
            if label:
                findings.append(
                    ctx.finding(
                        "obs-unstructured-log",
                        node,
                        f"{label} on the serving path is invisible to the "
                        "telemetry layer; record a span or log via the "
                        "structured trace logger",
                    )
                )
    return findings
