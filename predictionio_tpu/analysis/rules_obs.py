"""Observability rules for serving-path modules.

``print(...)`` and bare root-logger calls (``logging.info(...)`` et al.)
on the serving path are invisible to the telemetry layer: they bypass the
structured ``pio.trace`` JSON log (so a grep for a trace id misses them),
they can't be correlated with a request, and ``print`` additionally
flushes to an unbuffered fd inside the event loop. Serving code should
record spans (``predictionio_tpu.obs.tracing.Tracer.span``) or log
through a named module logger / the structured trace logger
(``predictionio_tpu.obs.tracing.get_trace_logger``).

Scope is the same ``LintConfig.serving_globs`` the host-sync family uses;
training scripts and CLIs may print freely.
"""

from __future__ import annotations

import ast
import re

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)

register_rule(
    "obs-unstructured-log",
    "obs",
    Severity.WARNING,
    "print()/bare logging.* call in a serving-path module; use the "
    "structured trace logger (obs.tracing.get_trace_logger) or a span "
    "so the output joins the request's trace",
)

register_rule(
    "obs-label-cardinality",
    "obs",
    Severity.WARNING,
    "metric label value derived from per-request data (query/user/entity "
    "ids) on the serving path; every distinct value allocates a series "
    "forever — use a bounded label, a span tag, or a histogram exemplar",
)

# direct root-logger methods: logging.info(...) etc. — a named logger
# (logging.getLogger(__name__).info) is fine and NOT matched
_ROOT_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical", "log"}
)


def _unstructured_label(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Name) and func.id == "print":
        return "print()"
    if isinstance(func, ast.Attribute) and func.attr in _ROOT_LOG_METHODS:
        d = astutil.dotted(func)
        if d and d == f"logging.{func.attr}":
            return d + "()"
    return None


# metric-write methods whose keyword arguments are label values
_METRIC_WRITE_METHODS = frozenset({"inc", "dec", "set", "set_total", "observe"})
# keyword arguments of those methods that are NOT labels: exemplars are
# *designed* to carry per-request trace ids (bounded: one per bucket)
_NON_LABEL_KWARGS = frozenset({"exemplar", "amount", "value"})
# identifier fragments that smell like per-request data. Deliberately NOT
# matching broad-but-bounded names like "status"/"endpoint"/"app_id" —
# canonical routes and status codes are finite; query payloads, user ids,
# entity ids, and trace ids are not.
_SUSPECT_NAME_RE = re.compile(
    r"(query|queries|payload|request|trace|span|user|entity|event|qid|uid)",
    re.IGNORECASE,
)


def _suspect_names(expr: ast.AST) -> list[str]:
    """Identifier-ish names appearing anywhere in a label-value expression
    that match the per-request pattern."""
    names: list[str] = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and _SUSPECT_NAME_RE.search(node.id):
            names.append(node.id)
        elif isinstance(node, ast.Attribute) and _SUSPECT_NAME_RE.search(
            node.attr
        ):
            names.append(node.attr)
    return names


@register_checker
def check_label_cardinality(ctx: FileContext):
    """Heuristic: in serving-path modules, a keyword argument to a metric
    write (``.inc(...)``/``.observe(...)``/``.set(...)``) is a label
    value; if its expression references per-request-looking data, each
    distinct request mints a new timeseries — the classic slow-leak that
    takes down both the scraper and the process. Constants are always
    fine; deliberate bounded cases suppress inline with a reason."""
    cfg = ctx.config
    if not matches_any_glob(ctx.path or ctx.display_path, cfg.serving_globs):
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_WRITE_METHODS
            and node.keywords
        ):
            continue
        for kw in node.keywords:
            if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                continue
            if isinstance(kw.value, ast.Constant):
                continue  # literal label values are bounded by definition
            suspects = _suspect_names(kw.value)
            if suspects:
                findings.append(
                    ctx.finding(
                        "obs-label-cardinality",
                        node,
                        f"label {kw.arg!r} is derived from per-request "
                        f"data ({', '.join(sorted(set(suspects)))}); every "
                        "distinct value allocates a metric series forever "
                        "— use a bounded label, a span tag, or an exemplar",
                    )
                )
    return findings


@register_checker
def check_unstructured_log(ctx: FileContext):
    cfg = ctx.config
    # absolute path when available: display paths are cwd-relative and
    # would silently miss the globs when linting from inside the package
    if not matches_any_glob(ctx.path or ctx.display_path, cfg.serving_globs):
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            label = _unstructured_label(node)
            if label:
                findings.append(
                    ctx.finding(
                        "obs-unstructured-log",
                        node,
                        f"{label} on the serving path is invisible to the "
                        "telemetry layer; record a span or log via the "
                        "structured trace logger",
                    )
                )
    return findings
