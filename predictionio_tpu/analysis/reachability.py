"""Entry-point declarations and reachability over the project call graph.

Rules no longer ask "does this file match a glob and this function match a
name list" — they ask "is this function *reachable* from a declared entry
point of category X". The LintConfig carries a tuple of ``EntryPoint``
declarations; everything a declared entry transitively calls inherits its
category, so a host sync three helpers below ``predict_batch_dispatch``
fires even though no glob names the helper's module.

Edge policy per category (see callgraph.py):

  - CALL edges always propagate.
  - NESTED edges (lexical containment) propagate for every category EXCEPT
    ``async-loop``: serving dispatch returns ``finalize`` closures that run
    on the serving path, so nested defs of a serving-reachable function are
    serving-reachable; but the fleet's executor-delegate pattern
    (``def _work(): blocking(); await loop.run_in_executor(None, _work)``)
    is precisely a nested def whose body is ALLOWED to block — async-loop
    reachability must not flow into it.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
from collections import deque
from typing import Iterable, Iterator

from .callgraph import FunctionNode, ProjectGraph

__all__ = [
    "EntryPoint",
    "Reachability",
    "CATEGORY_SERVING",
    "CATEGORY_PREDICT",
    "CATEGORY_TRAIN",
    "CATEGORY_EVAL",
    "CATEGORY_ASYNC",
    "glob_matches_path",
    "short_path",
]

CATEGORY_SERVING = "serving"
CATEGORY_PREDICT = "predict"
CATEGORY_TRAIN = "train"
CATEGORY_EVAL = "eval-scoring"
CATEGORY_ASYNC = "async-loop"

# categories whose reachability does NOT flow through lexical containment
_NO_NESTED = frozenset({CATEGORY_ASYNC})


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One declared root of a rule category.

    ``module_glob`` matches the file path (fnmatch, ``/``-normalized —
    ``*`` crosses separators, so ``*/tuning/*.py`` works for installed
    paths and fixture trees alike). ``function`` matches the function's
    bare name or qualname (``*`` = every def in the module).
    ``async_only`` restricts seeding to ``async def``s.
    """

    category: str
    module_glob: str
    function: str = "*"
    async_only: bool = False


def short_path(path: str) -> str:
    """Cwd-relative when that doesn't escape upward, else unchanged."""
    try:
        rel = os.path.relpath(path)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def glob_matches_path(path: str, glob: str) -> bool:
    """Same semantics as core.matches_any_glob: fnmatch on the
    ``/``-normalized path (``*`` crosses separators, so ``*/api/*.py``
    matches any depth)."""
    return fnmatch.fnmatch(path.replace("\\", "/"), glob)


class Reachability:
    """Per-category reachable sets with origin-entry tracking."""

    def __init__(
        self,
        graph: ProjectGraph,
        entry_points: Iterable[EntryPoint],
    ) -> None:
        self.graph = graph
        self.entry_points = tuple(entry_points)
        # category -> {function key -> entry key it was first reached from}
        self._reach: dict[str, dict[str, str]] = {}
        for category in {ep.category for ep in self.entry_points}:
            self._reach[category] = self._compute(category)

    # ------------------------------------------------------------ seeding
    def _seeds(self, category: str) -> list[str]:
        eps = [ep for ep in self.entry_points if ep.category == category]
        path_eps: dict[str, list[EntryPoint]] = {}
        seeds = []
        for fn in self.graph.functions.values():
            matching = path_eps.get(fn.path)
            if matching is None:
                matching = [
                    ep
                    for ep in eps
                    if glob_matches_path(fn.path, ep.module_glob)
                ]
                path_eps[fn.path] = matching
            for ep in matching:
                if ep.async_only and not fn.is_async:
                    continue
                if not (
                    fnmatch.fnmatch(fn.name, ep.function)
                    or fnmatch.fnmatch(fn.qualname, ep.function)
                ):
                    continue
                seeds.append(fn.key)
                break
        return seeds

    def _compute(self, category: str) -> dict[str, str]:
        follow_nested = category not in _NO_NESTED
        reached: dict[str, str] = {}
        queue: deque[tuple[str, str]] = deque()
        for seed in self._seeds(category):
            if seed not in reached:
                reached[seed] = seed
                queue.append((seed, seed))
        while queue:
            key, origin = queue.popleft()
            nexts: set[str] = set(self.graph.callees(key))
            if follow_nested:
                nexts |= self.graph.nested.get(key, set())
            for nxt in nexts:
                if nxt not in reached and nxt in self.graph.functions:
                    reached[nxt] = origin
                    queue.append((nxt, origin))
        return reached

    # ------------------------------------------------------------ queries
    def categories(self, key: str) -> frozenset[str]:
        return frozenset(
            cat for cat, reached in self._reach.items() if key in reached
        )

    def is_reachable(self, key: str, category: str) -> bool:
        return key in self._reach.get(category, ())

    def origin(self, key: str, category: str) -> FunctionNode | None:
        """The declared entry this function was first reached from."""
        entry_key = self._reach.get(category, {}).get(key)
        if entry_key is None:
            return None
        return self.graph.functions.get(entry_key)

    def iter_reachable_in_file(
        self, path: str, category: str
    ) -> Iterator[tuple[FunctionNode, FunctionNode | None]]:
        """(function, origin-entry) pairs for reachable functions defined
        in ``path``; origin is None when the function IS a seed."""
        reached = self._reach.get(category, {})
        for fn in self.graph.functions_in(path):
            entry_key = reached.get(fn.key)
            if entry_key is None:
                continue
            if entry_key == fn.key:
                yield fn, None
            else:
                yield fn, self.graph.functions.get(entry_key)

    def reach_note(self, fn: FunctionNode, origin: FunctionNode | None) -> str:
        """Message suffix explaining WHY a function is in scope: empty for
        a declared entry itself, the originating entry otherwise."""
        if origin is None:
            return ""
        return (
            f"; reachable from entry point {origin.qualname!r} "
            f"({short_path(origin.path)}:{origin.lineno})"
        )

    def entry_module_globs(self, category: str) -> tuple[str, ...]:
        """The module globs declared for a category — used by rules that
        also scan module-level statements (reachability is def-scoped)."""
        return tuple(
            ep.module_glob
            for ep in self.entry_points
            if ep.category == category
        )
