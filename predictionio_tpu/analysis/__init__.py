"""TPU-aware static analysis (`pio lint`).

AST-based checks that catch the classic JAX serving failure modes at review
time instead of at 3am on a pod: tracer-unsafe Python control flow inside
jitted functions, recompile hazards (unhashable/scalar args, jit wrappers
built per request, mutable closure capture), host-sync stalls on the serving
path, unlocked shared state in threaded modules, and storage backends that
drift from the ``storage/base.py`` abstract contract.

Since ISSUE 16 the engine is whole-program: a cross-file call graph
(``callgraph.py``) plus reachability from declared entry points
(``reachability.py``, ``LintConfig.entry_points``) scope the
context-sensitive rules, and two new families guard the pod-scale work:
``mesh-*`` (axis-name agreement, single-host materialization, per-shard
top-k merging) and ``async-blocking-call`` (blocking I/O on fleet event
loops, transitively through the call graph).

Public surface:

- :func:`analyze_paths` / :func:`analyze_source` — run the rule registry.
- :class:`Finding`, :class:`Severity`, :class:`LintConfig`,
  :class:`Report`, :class:`EntryPoint`.
- ``predictionio_tpu.analysis.cli:main`` — the ``pio lint`` / ``lint``
  console entry point.

Inline suppression: ``# pio-lint: disable=rule-id[,rule-id...] -- reason``
on the offending line (or alone on the line above); file-level with
``# pio-lint: disable-file=rule-id``. Suppressions should carry a reason.

This package must stay importable without jax/numpy: `pio lint` runs in
CI and pre-commit hooks where pulling in an accelerator runtime (or a
wedged TPU tunnel plugin) is exactly what we are trying to avoid.
"""

from predictionio_tpu.analysis.core import (
    Finding,
    LintConfig,
    Report,
    Severity,
    all_rules,
    analyze_paths,
    analyze_source,
)
from predictionio_tpu.analysis.reachability import EntryPoint

# importing the rule modules registers their checkers
from predictionio_tpu.analysis import (  # noqa: F401  (registration side effect)
    rules_async,
    rules_concurrency,
    rules_fleet,
    rules_hostsync,
    rules_mesh,
    rules_obs,
    rules_recompile,
    rules_storage,
    rules_stream,
    rules_tracer,
    rules_train,
)

__all__ = [
    "EntryPoint",
    "Finding",
    "LintConfig",
    "Report",
    "Severity",
    "all_rules",
    "analyze_paths",
    "analyze_source",
]
